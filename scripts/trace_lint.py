"""Trace-lint CLI: compile-surface static analysis + fingerprint gate.

Two levels, mirroring partisan's ``partisan_analysis.erl`` static walk
(SURVEY crosswalk) transplanted to the traced-Python world:

* **Level 1** (default, pure AST — JAX is never imported): lint every
  module under ``partisan_tpu/`` for unroll bombs, traced-value
  coercions/formatting, config forks, and host-twin drift.  Exit 1 on
  any unsuppressed finding.  Suppress intentional sites with
  ``# trace-lint: allow(<rule>): reason`` directly above the line —
  a pragma with no reason or no matching finding is itself an error.
* **Level 2** (``--check`` / ``--bless``, lower-only — traces and
  ``.lower()``s the flagship entrypoints, never invokes XLA): diff the
  program fingerprints (jaxpr eqn counts, StableHLO collective counts,
  lowered-text size) against the committed ``LINT_fingerprints.json``.
  ``--check`` fails on any collective-count change or >10% eqn growth;
  ``--bless`` rewrites the golden after an intended program change.

Usage: python scripts/trace_lint.py            # Level 1 only
       python scripts/trace_lint.py --check    # Level 1 + golden diff
       python scripts/trace_lint.py --bless    # regenerate goldens
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "partisan_tpu")
GOLDEN = os.path.join(REPO, "LINT_fingerprints.json")


def _load_lint_engine():
    """Import partisan_tpu.verify.lint WITHOUT executing partisan_tpu's
    package __init__ (which imports JAX — Level 1 must stay pure AST,
    runnable on a box with no accelerator stack at all)."""
    for name, path in (("partisan_tpu", PKG),
                       ("partisan_tpu.verify", os.path.join(PKG, "verify"))):
        if name not in sys.modules:
            stub = types.ModuleType(name)
            stub.__path__ = [path]
            stub.__trace_lint_stub__ = True
            sys.modules[name] = stub
    spec = importlib.util.spec_from_file_location(
        "partisan_tpu.verify.lint",
        os.path.join(PKG, "verify", "lint", "__init__.py"),
        submodule_search_locations=[os.path.join(PKG, "verify", "lint")])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["partisan_tpu.verify.lint"] = mod
    spec.loader.exec_module(mod)
    assert "jax" not in sys.modules, "Level-1 lint must not import JAX"
    return mod


def run_lint() -> int:
    lint = _load_lint_engine()
    findings = lint.lint_tree(PKG, root=REPO)
    print(lint.format_report(findings))
    return 1 if findings else 0


def _jax_env():
    """8-device virtual CPU mesh, set BEFORE the first jax import (same
    setup as tests/conftest.py / suite_matrix.py)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def run_fingerprints(bless: bool) -> int:
    _jax_env()
    sys.path.insert(0, REPO)
    # a prior Level-1 pass leaves jax-free package stubs in sys.modules;
    # evict them (and the lint modules hanging off them) so the real
    # partisan_tpu package __init__ executes for the builders
    if getattr(sys.modules.get("partisan_tpu"), "__trace_lint_stub__",
               False):
        for name in [n for n in sys.modules
                     if n == "partisan_tpu"
                     or n.startswith("partisan_tpu.")]:
            del sys.modules[name]
    from partisan_tpu.verify.lint import fingerprint as fp

    t0 = time.time()

    def progress(name):
        print(f"  lowering {name} ... [{time.time() - t0:5.1f}s]",
              flush=True)

    if bless:
        fps = fp.bless(GOLDEN, progress=progress)
        print(f"blessed {len(fps)} fingerprints -> {GOLDEN} "
              f"({time.time() - t0:.1f}s)")
        return 0
    if not os.path.exists(GOLDEN):
        print(f"trace-lint: missing {GOLDEN} — run --bless first",
              file=sys.stderr)
        return 1
    errors = fp.check(GOLDEN, progress=progress)
    if errors:
        print(f"trace-lint: fingerprint gate FAILED "
              f"({len(errors)} regressions, {time.time() - t0:.1f}s):")
        for e in errors:
            print(f"  {e}")
        return 1
    print(f"trace-lint: fingerprint gate clean "
          f"({time.time() - t0:.1f}s)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    g = ap.add_mutually_exclusive_group()
    g.add_argument("--check", action="store_true",
                   help="Level 1 lint + fingerprint diff vs the golden")
    g.add_argument("--bless", action="store_true",
                   help="regenerate LINT_fingerprints.json (no lint)")
    args = ap.parse_args(argv)

    if args.bless:
        return run_fingerprints(bless=True)
    rc = run_lint()
    if args.check:
        # lint findings and fingerprint regressions both surface; the
        # exit code is the OR so CI sees one gate
        rc = max(rc, run_fingerprints(bless=False))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
