"""Micro-ablation of hyparview_dense.bulk_passive_merge internals at
N=2^16 (the phase ablation showed the merge is ~2/3 of the round; the
[W,W]->sort dedup swap moved nothing, so the cost is elsewhere in it).

Times standalone jitted variants on representative inputs: which of
{active-mask, value-sort, threefry uniform, top_k} pays?

Usage: python scripts/profile_merge.py [--n 65536]
"""

from __future__ import annotations

import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from partisan_tpu.ops.bitset import mix32  # noqa: E402

P, A, K = 30, 6, 32     # passive width, active width, candidate width


def inputs(n, seed):
    k = jax.random.PRNGKey(seed)
    ka, kp, kc = jax.random.split(k, 3)
    active = jax.random.randint(ka, (n, A), -1, n, jnp.int32)
    passive = jax.random.randint(kp, (n, P), -1, n, jnp.int32)
    cands = jax.random.randint(kc, (n, K), -1, n, jnp.int32)
    return active, passive, cands


def make_variant(which, n):
    ids = jnp.arange(n, dtype=jnp.int32)

    def merge(active, passive, cands, key):
        cat = jnp.concatenate([passive, cands], axis=1)
        ok = (cat >= 0) & (cat != ids[:, None])
        if which != "no_activemask":
            ok &= ~jnp.any(cat[:, :, None] == active[:, None, :], axis=-1)
        big = jnp.int32(1) << 30
        if which == "no_sort":
            sv, first = jnp.where(ok, cat, big), jnp.ones(cat.shape, bool)
        else:
            sv = jnp.sort(jnp.where(ok, cat, big), axis=1)
            first = jnp.concatenate(
                [jnp.ones((n, 1), bool), sv[:, 1:] != sv[:, :-1]], axis=1)
        ok2 = (sv < big) & first
        if which == "hash_pri":
            h = mix32(sv.astype(jnp.uint32)
                      ^ jax.random.bits(key, (), jnp.uint32))
            pri = jnp.where(ok2, h.astype(jnp.float32), -1.0)
        else:
            pri = jnp.where(ok2, jax.random.uniform(key, sv.shape), -1.0)
        if which == "no_topk":
            return jnp.where(ok2, sv, -1)[:, :P]
        if which == "sort2":
            masked = jnp.where(ok2, sv, -1)
            _, out = jax.lax.sort((-pri, masked), dimension=1, num_keys=1)
            return out[:, :P]
        if which == "approx":
            _, keep = jax.lax.approx_max_k(pri, P)
            return jnp.take_along_axis(jnp.where(ok2, sv, -1), keep,
                                       axis=1)
        if which == "packed":
            # single-operand uint32 sort: 16-bit random rank | low bits
            # of a shuffled value surrogate; then gather by recovered
            # column index.  rank<<16 | column  (column fits 16 bits)
            col = jnp.arange(sv.shape[1], dtype=jnp.uint32)[None, :]
            h = mix32(sv.astype(jnp.uint32) * jnp.uint32(2654435761)
                      ^ jax.random.bits(key, (), jnp.uint32))
            rank = jnp.where(ok2, h >> 16, jnp.uint32(0xFFFF))
            packed = (rank << 16) | col
            srt = jnp.sort(packed, axis=1)[:, :P]
            keep = (srt & jnp.uint32(0xFFFF)).astype(jnp.int32)
            out = jnp.take_along_axis(jnp.where(ok2, sv, -1), keep, axis=1)
            return jnp.where((srt >> 16) == 0xFFFF, -1, out)
        _, keep = jax.lax.top_k(pri, P)
        return jnp.take_along_axis(jnp.where(ok2, sv, -1), keep, axis=1)

    def run(active, passive, cands, key, rounds=100):
        def body(c, _):
            p, k = c
            k1, k2 = jax.random.split(k)
            return (merge(active, p, cands, k1), k2), None

        (p, _), _ = jax.lax.scan(body, (passive, key), None, length=rounds)
        return p

    return jax.jit(run, static_argnums=(4,))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 16)
    ap.add_argument("--rounds", type=int, default=100)
    args = ap.parse_args()
    for which in ("full", "no_activemask", "no_sort", "hash_pri",
                  "no_topk", "sort2", "approx", "packed"):
        fn = make_variant(which, args.n)
        a, p, c = inputs(args.n, 1)
        out = fn(a, p, c, jax.random.PRNGKey(2), args.rounds)
        float(jnp.sum(out))
        rates = []
        for t in range(3):
            a, p, c = inputs(args.n, 10 + t)
            t0 = time.perf_counter()
            out = fn(a, p, c, jax.random.PRNGKey(3 + t), args.rounds)
            float(jnp.sum(out))
            rates.append(args.rounds / (time.perf_counter() - t0))
        print(f"{which:16s} {statistics.median(rates):8.1f} merges/s")


if __name__ == "__main__":
    main()
