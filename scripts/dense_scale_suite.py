"""ISSUE 9 scale suite: explicit-SPMD vs implicit (GSPMD auto-
partitioned) dense rounds across N.

Two arms per (model, N):

* ``implicit`` — the unsharded round (models/hyparview_dense.py /
  scamp_dense.py) jitted over state placed with ``node_sharding``:
  XLA's partitioner inserts whatever collectives it likes (19
  all-gathers per HyParView round at the seed).
* ``explicit`` — the manual-SPMD round (parallel/dense_dataplane.py):
  one bucketed all-to-all + one metrics all-reduce per round, budget
  asserted at compile time.

One JSON line per (model, N, arm) is appended to
``BENCH_dense_scale.jsonl``; rows also land in ``results.csv``.  Runs
that die (OOM / worker fault at the largest N) are ANNOTATED as rows
with an ``error`` field, not dropped — a missing row reads as "not
attempted", which is the wrong record.  Off-TPU runs carry
``cpu_fallback: true``.

Usage:
  python scripts/dense_scale_suite.py                  # 2^16 + 2^18
  python scripts/dense_scale_suite.py --n 1048576      # add 2^20
  python scripts/dense_scale_suite.py --smoke          # CI: N=4096, one window
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import partisan_tpu as pt  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# rounds shrink with N: the point is rounds/sec at scale, not a soak
ROUNDS = {4096: 40, 1 << 16: 40, 1 << 18: 12, 1 << 20: 4}


def _cfg(model: str, n: int) -> pt.Config:
    if model == "hyparview":
        return pt.Config(n_nodes=n, shuffle_interval=4,
                         random_promotion_interval=2)
    return pt.Config(n_nodes=n)


def _counts(stats) -> dict:
    return {k: v for k, v in stats["counts"].items() if v}


def run_implicit(model: str, n: int, rounds: int, mesh, churn: float):
    from partisan_tpu.parallel.mesh import collective_stats, node_sharding
    cfg = _cfg(model, n)
    if model == "hyparview":
        from partisan_tpu.models.hyparview_dense import (dense_init,
                                                         make_dense_round,
                                                         run_dense)
        s0 = dense_init(cfg)
        run = lambda s: run_dense(s, rounds, cfg, churn)  # noqa: E731
        step = make_dense_round(cfg, churn)
    else:
        from partisan_tpu.models.scamp_dense import (dense_scamp_init,
                                                     make_dense_scamp_round,
                                                     run_dense_scamp)
        s0 = dense_scamp_init(cfg)
        run = lambda s: run_dense_scamp(s, rounds, cfg, churn)  # noqa: E731
        step = make_dense_scamp_round(cfg, churn)
    st = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, node_sharding(mesh, x)), s0)
    comms = _counts(collective_stats(jax.jit(step).lower(st).compile()))
    jax.block_until_ready(run(st))  # warm
    t0 = time.perf_counter()
    jax.block_until_ready(run(st))
    return time.perf_counter() - t0, comms


def run_explicit(model: str, n: int, rounds: int, mesh, churn: float,
                 stream=None):
    from partisan_tpu.parallel import dense_dataplane as dd
    from partisan_tpu.parallel.mesh import assert_collective_budget
    cfg = _cfg(model, n)
    n_dev = len(mesh.devices.flat)
    step = dd.make_sharded_dense_round(cfg, mesh, model=model, churn=churn)
    init = (dd.sharded_dense_init if model == "hyparview"
            else dd.sharded_scamp_init)
    st = dd.place_sharded(init(cfg, n_dev), mesh)
    stats = assert_collective_budget(
        step.lower(st).compile(), max_collectives=3, max_bytes=1 << 40,
        forbid=("all-gather",),
        max_counts={"all-to-all": 1, "all-reduce": 2,
                    "collective-permute": 2})
    # --stream (ISSUE 14): the per-round metric drain rides OUTSIDE the
    # shard_map'd step on already-replicated values, so the collective
    # budget asserted above is untouched; both the warm and the timed
    # pass run the streamed program (what streams is what's measured)
    jax.block_until_ready(
        dd.run_sharded_chunked(step, st, rounds, cfg, stream=stream))
    if stream is not None:
        # the synthetic round counter spans the warm pass too — reset so
        # the timed heartbeat reads 0..rounds and stream_rows == rounds
        jax.effects_barrier()
        stream.rows_streamed, stream.last_round = 0, -1
    t0 = time.perf_counter()
    jax.block_until_ready(
        dd.run_sharded_chunked(step, st, rounds, cfg, stream=stream))
    if stream is not None:
        jax.effects_barrier()
    return time.perf_counter() - t0, _counts(stats)


def run_aot(model: str, n: int, rounds: int, mesh, churn: float):
    """ISSUE 17 arm: the explicit round served by the AOT export plane.

    The first run at a given (model, N, churn) has no artifact, so it
    compiles ONCE and exports — recorded as ``aot: "export"`` with the
    compile wall in ``setup_seconds`` (the load-not-compile escape
    hatch: the cost is paid, named, and never paid again).  Every later
    run deserializes the artifact instead of compiling
    (``aot: "load"``), so the row's ``setup_seconds`` is the measured
    cold-start the plane removes at this N.  Rounds execute through a
    plain host loop over the deserialized round (the scan runner would
    be a different program than the exported one)."""
    from partisan_tpu import aot
    from partisan_tpu.parallel import dense_dataplane as dd
    cfg = _cfg(model, n)
    n_dev = len(mesh.devices.flat)
    init = (dd.sharded_dense_init if model == "hyparview"
            else dd.sharded_scamp_init)
    st = dd.place_sharded(init(cfg, n_dev), mesh)
    # churn bakes into the program as a constant, so it keys the name:
    # a signature match alone must never adopt a different-churn twin
    name = (f"dense_scale_{model}_n{n}x{n_dev}_churn"
            + str(churn).replace(".", "p"))
    t0 = time.perf_counter()
    prog = aot.maybe_load(name)
    mode = "load"
    if prog is None or not prog.matches((st,)):
        mode = "export"
        step = dd.make_sharded_dense_round(cfg, mesh, model=model,
                                           churn=churn)
        aot.export_entry(name, step, (st,))
        prog = aot.load(name)
    setup = time.perf_counter() - t0
    jax.block_until_ready(prog(st))  # warm the dispatch path
    t1 = time.perf_counter()
    s = st
    for _ in range(rounds):
        s, _m = prog(s)
    jax.block_until_ready(s)
    secs = time.perf_counter() - t1
    return secs, {"aot": mode, "setup_seconds": round(setup, 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, nargs="*", default=[1 << 16, 1 << 18])
    ap.add_argument("--models", nargs="*", default=["hyparview", "scamp"])
    ap.add_argument("--arms", nargs="*", default=["implicit", "explicit"],
                    help="any of: implicit explicit aot (the aot arm "
                         "loads — or first-run exports — the explicit "
                         "round via partisan_tpu.aot instead of "
                         "compiling, and records setup_seconds)")
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument("--rounds", type=int, default=None,
                    help="override the per-N round count (slow boxes)")
    ap.add_argument("--arm-timeout", type=int, default=None,
                    help="wall ceiling per arm in seconds; a breach is "
                         "recorded as an annotated error row (SIGALRM — "
                         "an externally killed run leaves no record)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI row: N=4096, one window, both arms")
    ap.add_argument("--stream", action="store_true",
                    help="explicit arm: drain per-round metrics to the "
                         "host MID-SCAN (ordered io_callback) with a "
                         "live heartbeat; zero extra collectives, but "
                         "the streamed program never persistent-caches")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_dense_scale.jsonl"))
    ap.add_argument("--csv", default=os.path.join(REPO, "results.csv"))
    args = ap.parse_args()
    if args.smoke:
        args.n, args.models = [4096], ["hyparview"]

    from partisan_tpu.parallel.mesh import make_mesh
    n_dev = len(jax.devices())
    mesh = make_mesh(n_devices=n_dev)
    platform = jax.devices()[0].platform
    fallback = platform != "tpu"

    for model in args.models:
        for n in args.n:
            rounds = args.rounds or ROUNDS.get(n, max(4, (1 << 22) // n))
            for arm in args.arms:
                row = {"config": f"dense_scale_{model}_{n}_{arm}",
                       "model": model, "n_nodes": n, "arm": arm,
                       "rounds": rounds, "n_devices": n_dev,
                       "platform": platform, "cpu_fallback": fallback,
                       "churn": args.churn}
                fn = {"implicit": run_implicit, "explicit": run_explicit,
                      "aot": run_aot}[arm]
                kw = {}
                if args.stream and arm == "explicit":
                    from partisan_tpu.telemetry import StreamSpec

                    def _beat(mrow, _rounds=rounds):
                        rnd = int(mrow.get("round", 0))
                        if rnd % 16 == 0 or rnd == _rounds:
                            print(f"    [stream] round {rnd}/{_rounds} "
                                  f"live={mrow.get('live')}", flush=True)
                    kw["stream"] = StreamSpec(on_row=_beat)
                if args.arm_timeout:
                    def _alarm(signum, frame):
                        raise TimeoutError(
                            f"arm exceeded --arm-timeout="
                            f"{args.arm_timeout}s wall ceiling")
                    signal.signal(signal.SIGALRM, _alarm)
                    signal.alarm(args.arm_timeout)
                try:
                    secs, comms = fn(model, n, rounds, mesh, args.churn,
                                     **kw)
                    row["seconds"] = round(secs, 4)
                    row["rounds_per_sec"] = round(rounds / secs, 4)
                    if arm == "aot":
                        row.update(comms)  # {"aot": mode, "setup_seconds"}
                    else:
                        row["collectives_per_round"] = comms
                    if "stream" in kw:
                        row["stream_rows"] = kw["stream"].rows_streamed
                except Exception as e:  # noqa: BLE001 — annotate, don't drop
                    traceback.print_exc()
                    row["error"] = f"{type(e).__name__}: {e}"[:300]
                finally:
                    if args.arm_timeout:
                        signal.alarm(0)
                with open(args.out, "a") as f:
                    f.write(json.dumps(row) + "\n")
                # unified bench ledger (ISSUE 18): same row, canonical
                # BenchRow schema; legacy artifacts above unchanged.
                # Smoke runs land in /tmp so CI never dirties the
                # committed trajectory (same policy as control_suite).
                if "error" not in row:
                    from partisan_tpu.telemetry import benchplane
                    ledger_path = os.environ.get(
                        "PARTISAN_BENCH_LEDGER") or (
                        "/tmp/BENCH_ledger_smoke.jsonl"
                        if args.smoke else None)
                    benchplane.append_rows_nonfatal([benchplane.make_row(
                        "dense_scale", f"{model}_{arm}",
                        config={"churn": args.churn,
                                "stream": bool(args.stream)},
                        n_nodes=n, rounds=rounds, n_devices=n_dev,
                        rounds_per_sec=row["rounds_per_sec"],
                        wall_s=row["seconds"],
                        metrics={k: row[k] for k in
                                 ("collectives_per_round", "aot",
                                  "setup_seconds", "stream_rows")
                                 if k in row})], ledger_path)
                if "error" not in row and not args.smoke:
                    comms_s = ("+".join(
                        f"{k}:{v}" for k, v in
                        sorted(row.get("collectives_per_round",
                                       {}).items()))
                        or f"aot={row.get('aot')}")
                    with open(args.csv, "a") as f:
                        f.write(f"{row['config']}_{platform},{n},{rounds},"
                                f"{row['seconds']},{row['rounds_per_sec']},"
                                f"\"arm={arm},collectives={comms_s},"
                                f"fallback={fallback}\"\n")
                print("bench:", json.dumps(row))


if __name__ == "__main__":
    main()
