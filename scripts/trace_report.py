"""Message-lifecycle trace summarizer: span stats, latency breakdown,
critical path, per-message drill-down, Perfetto export.

Consumes a span-event stream in the JSONL format
``telemetry.tracer.write_spans`` persists (one ``{"rnd", "ev", "src",
"dst", "typ", "born", "seq"}`` object per line, ``ev`` as the lifecycle
event NAME) and prints ONE JSON summary line:

  * ``events`` / ``spans`` — stream size and distinct (src, seq) spans;
  * ``per_event`` — event count by lifecycle stage (emitted, held,
    delivered, acked, retransmitted, dead_lettered, shed, chaos_*);
  * ``latency`` — the span latency decomposition aggregated over
    completed spans: mean/max total plus mean queue / retry / transit /
    partition_wait rounds (where the rounds went, not just how many);
  * ``critical_path`` — the delivery dependency chain that determined
    the last delivery (oldest first).

Modes:
  * ``--message SRC,SEQ`` reports ONE span instead: its full event
    timeline, attempts, and latency decomposition;
  * ``--perfetto OUT.json`` additionally writes the Chrome-trace view
    (message-span slices + lifecycle instants) for ui.perfetto.dev.

Run:  python scripts/trace_report.py SPANS.jsonl [--top 10]
          [--typ-names a,b,c] [--message 3,42] [--perfetto out.json]
          [--pretty]
"""

import argparse
import collections
import json
import sys

sys.path.insert(0, ".")  # run from the repo root

from partisan_tpu.telemetry import tracer  # noqa: E402


def span_row(sp, typ_names=None):
    """One span as a JSON-ready dict (the --message drill-down body)."""
    def typ_label(t):
        if typ_names is not None and 0 <= t < len(typ_names):
            return typ_names[t]
        return t
    return {
        "src": sp.src, "seq": sp.seq, "typ": typ_label(sp.typ),
        "dst": sp.dst, "born": sp.born, "attempts": sp.attempts,
        "delivered_rnd": sp.delivered_rnd, "acked_rnd": sp.acked_rnd,
        "latency": sp.latency(),
        "timeline": [{"rnd": e.rnd, "ev": e.name, "dst": e.dst}
                     for e in sorted(sp.events,
                                     key=lambda e: (e.rnd, e.ev))],
    }


def summarize(events, top=10, typ_names=None):
    spans = tracer.trace_spans(events)
    per_event = collections.Counter(e.name for e in events)
    done = [sp for sp in spans.values()
            if sp.delivered_rnd is not None or sp.acked_rnd is not None]
    lats = [sp.latency() for sp in done]

    def mean(key):
        return (round(sum(l[key] for l in lats) / len(lats), 2)
                if lats else 0.0)

    slow = sorted(done, key=lambda sp: -sp.latency()["total"])[:top]
    path = tracer.critical_path(tracer.deliveries(events))
    return {
        "events": len(events),
        "spans": len(spans),
        "completed": len(done),
        "per_event": dict(sorted(per_event.items())),
        "latency": {
            "mean_total": mean("total"),
            "max_total": max((l["total"] for l in lats), default=0),
            "mean_queue": mean("queue"),
            "mean_retry": mean("retry"),
            "mean_transit": mean("transit"),
            "mean_partition_wait": mean("partition_wait"),
        },
        "slowest": [{"src": sp.src, "seq": sp.seq,
                     "total": sp.latency()["total"]} for sp in slow],
        "critical_path": [list(d) for d in path],
        "critical_path_len": len(path),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("spans", help="span-event JSONL (write_spans format)")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--typ-names", default=None,
                    help="comma-separated wire-tag names")
    ap.add_argument("--message", default=None, metavar="SRC,SEQ",
                    help="drill into one span (trace-id src,seq)")
    ap.add_argument("--perfetto", default=None, metavar="OUT",
                    help="also write the Chrome-trace span view")
    ap.add_argument("--pretty", action="store_true",
                    help="human-readable table on stderr")
    args = ap.parse_args()

    events = tracer.read_spans(args.spans)
    typ_names = args.typ_names.split(",") if args.typ_names else None

    if args.perfetto:
        from partisan_tpu.telemetry import perfetto
        perfetto.write_chrome_trace(
            args.perfetto, spans=tracer.trace_spans(events).values(),
            typ_names=typ_names)

    if args.message is not None:
        src, seq = (int(x) for x in args.message.split(","))
        sp = tracer.trace_spans(events).get((src, seq))
        if sp is None:
            print(json.dumps({"src": src, "seq": seq, "found": False}))
            sys.exit(1)
        print(json.dumps({"found": True, **span_row(sp, typ_names)}))
        return

    s = summarize(events, top=args.top, typ_names=typ_names)
    print(json.dumps(s))

    if args.pretty:
        p = lambda *a: print(*a, file=sys.stderr)  # noqa: E731
        p(f"{s['events']} events, {s['spans']} spans "
          f"({s['completed']} completed)")
        p("per event: " + ", ".join(f"{k}={v}"
                                    for k, v in s["per_event"].items()))
        lat = s["latency"]
        p(f"latency: mean {lat['mean_total']} rounds "
          f"(queue {lat['mean_queue']}, retry {lat['mean_retry']}, "
          f"transit {lat['mean_transit']}, partition_wait "
          f"{lat['mean_partition_wait']}), max {lat['max_total']}")
        p(f"critical path ({s['critical_path_len']} links):")
        for rnd, src, dst, typ, seq in s["critical_path"]:
            p(f"  r{rnd:4d}  {src} -> {dst}  typ={typ} seq={seq}")


if __name__ == "__main__":
    main()
