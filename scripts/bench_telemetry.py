"""Telemetry overhead bench (ISSUE 1 acceptance): HyParView at N=4096
with the full default metric set recorded in-scan (window >= 64 rounds)
must cost <= 5% rounds/sec versus telemetry disabled, while producing
non-trivial ``msgs_delivered`` / ``out_dropped`` / ``isolated`` /
``rounds_per_sec`` in both the JSONL and Prometheus outputs.

All arms run the SAME windowed-scan shape with one host sync per
window; the only difference is the ring + collectors.  Results land in
``BENCH_telemetry.jsonl`` (per-round + per-window rows) and
``BENCH_telemetry.prom`` (exposition snapshot); stdout prints one JSON
summary line (existing keys unchanged).

ISSUE 3 adds the flight-recorder column: a third arm co-carries the
message flight ring (``--flight-cap`` slots/round, head-capped +
counted) through the same scans and reports ``flight_overhead_pct``
against the telemetry arm (the <= 5% recorder-ON bar).  The recorder-OFF
bar (<= 1%) is structural: with ``flight=None`` the runner compiles a
byte-identical program to the pre-recorder harness, so the telemetry
arm IS the recorder-off arm — its ``overhead_pct`` vs plain is reported
unchanged.

ISSUE 14 adds the streaming column: a fourth arm drains every round's
packed metric row to the host MID-SCAN through the ordered
``io_callback`` (``telemetry.observatory.StreamSpec``) and reports
``stream_overhead_pct`` against the windowed telemetry arm (the <= 5%
streaming bar).  The stream-OFF bar is structural again: ``stream=None``
compiles a byte-identical program.  The streaming program embeds a host
callback, so it is never persistently cacheable — this arm recompiles
every bench run (compile time stays outside the timed windows).

ISSUE 16 adds the tracer column: a fifth arm co-carries the message
lifecycle span ring (``--trace-cap`` event slots/round, head-capped +
counted) through the same scans and reports ``tracer_overhead_pct``
against the FLIGHT arm (the <= 5% span-plane bar: both arms carry one
recorder ring, so the delta prices the per-event id arithmetic + the
lifecycle captures, not the ring itself).  The tracer-OFF bar is
structural once more: ``trace=None`` compiles a byte-identical program.

Run:  JAX_PLATFORMS=cpu python scripts/bench_telemetry.py [--n 4096]
"""

import argparse
import json
import os
import statistics
import sys
import time

import jax

sys.path.insert(0, ".")  # run from the repo root

import partisan_tpu as pt                                   # noqa: E402
from partisan_tpu import peer_service, telemetry            # noqa: E402
from partisan_tpu.models.hyparview import HyParView         # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--window", type=int, default=64)
    ap.add_argument("--windows", type=int, default=3,
                    help="timed windows per arm (after 1 warmup window)")
    ap.add_argument("--flight-cap", type=int, default=4096,
                    help="flight-recorder slots per round (head-capped "
                         "+ counted beyond)")
    ap.add_argument("--trace-cap", type=int, default=4096,
                    help="lifecycle-tracer event slots per round "
                         "(head-capped + counted beyond)")
    args = ap.parse_args()
    n, window = args.n, args.window

    cfg = pt.Config(n_nodes=n, inbox_cap=8)
    proto = HyParView(cfg)
    world0 = pt.init_world(cfg, proto)
    # binary-tree contacts spread the join storm (vs. a single-contact
    # storm that serializes on node 0's inbox)
    world0 = peer_service.cluster(
        world0, proto, [(i, (i - 1) // 2) for i in range(1, n)])

    registry = telemetry.default_registry()
    step = pt.make_step(cfg, proto, donate=False)

    # -- telemetry-disabled arm: same windowed scan, metrics dict dropped
    #    (XLA dead-code-eliminates the unused counter taps)
    @jax.jit
    def plain_window(world):
        def body(w, _):
            w2, _m = step(w)
            return w2, None
        w2, _ = jax.lax.scan(body, world, None, length=window)
        return w2

    telem_window = telemetry.make_window_runner(
        cfg, proto, registry, window, step=step)

    jsonl = telemetry.JsonlSink("BENCH_telemetry.jsonl")
    prom = telemetry.PrometheusSink(registry, path="BENCH_telemetry.prom")
    timeline = telemetry.RoundTimeline()
    ring = telemetry.make_ring(registry, window)

    # -- telemetry arm: warmup window (compile + join storm, captured so
    #    the artifact holds the non-trivial out_dropped/isolated phase),
    #    then timed steady-state windows
    all_rows = []

    def telem_run(world, ring, timed):
        nonlocal all_rows
        t0 = time.perf_counter()
        world, ring = telem_window(world, ring)
        rows, ring = telemetry.flush(ring, registry)
        dt = time.perf_counter() - t0
        wrow = timeline.observe(window, dt)
        for row in rows:
            jsonl.write_row(row)
            prom.write_row(row)
        jsonl.write_row(wrow)
        prom.write_row(wrow)
        all_rows += rows
        return world, ring, (dt if timed else None)

    wt, ring, _ = telem_run(world0, ring, timed=False)
    telem_secs = []
    for _ in range(args.windows):
        wt, ring, dt = telem_run(wt, ring, timed=True)
        telem_secs.append(dt)

    # -- flight arm (ISSUE 3): telemetry + the message flight recorder
    #    co-carried through the same windowed scan; one extra
    #    [window, cap, 6] transfer per window (timed), head-cap counted
    fspec = telemetry.FlightSpec(window=window, cap=args.flight_cap)
    flight_window = telemetry.make_window_runner(
        cfg, proto, registry, window, flight=fspec)
    fring = telemetry.make_flight_ring(fspec)
    flight_entries_total = 0
    flight_overflow_total = 0

    def flight_run(world, ring, fring, timed):
        nonlocal flight_entries_total, flight_overflow_total
        t0 = time.perf_counter()
        world, ring, fring = flight_window(world, ring, fring)
        _rows, ring = telemetry.flush(ring, registry)
        frows, ovf, fring = telemetry.flight_flush(fring)
        dt = time.perf_counter() - t0
        flight_entries_total += int((frows[..., 0] >= 0).sum())
        flight_overflow_total += ovf
        return world, ring, fring, (dt if timed else None)

    fring2 = telemetry.make_ring(registry, window)
    wf, fring2, fring, _ = flight_run(world0, fring2, fring, timed=False)
    flight_secs = []
    for _ in range(args.windows):
        wf, fring2, fring, dt = flight_run(wf, fring2, fring, timed=True)
        flight_secs.append(dt)

    # -- tracer arm (ISSUE 16): telemetry + the message lifecycle span
    #    ring co-carried through the same windowed scan; one extra
    #    [window, cap, 7] transfer per window (timed), head-cap counted
    tspec = telemetry.TraceSpec(window=window, cap=args.trace_cap)
    trace_window = telemetry.make_window_runner(
        cfg, proto, registry, window, trace=tspec)
    tring = telemetry.make_trace_ring(tspec)
    trace_events_total = 0
    trace_overflow_total = 0

    def trace_run(world, ring, tring, timed):
        nonlocal trace_events_total, trace_overflow_total
        t0 = time.perf_counter()
        world, ring, _fr, tring, _a = trace_window(
            world, ring, None, tring, None)
        _rows, ring = telemetry.flush(ring, registry)
        trows, tovf, tring = telemetry.trace_flush(tring)
        dt = time.perf_counter() - t0
        trace_events_total += int((trows[..., 0] >= 0).sum())
        trace_overflow_total += tovf
        return world, ring, tring, (dt if timed else None)

    tring2 = telemetry.make_ring(registry, window)
    wtr, tring2, tring, _ = trace_run(world0, tring2, tring, timed=False)
    trace_secs = []
    for _ in range(args.windows):
        wtr, tring2, tring, dt = trace_run(wtr, tring2, tring, timed=True)
        trace_secs.append(dt)

    # -- streaming arm (ISSUE 14): the same windowed scan with every
    #    round's packed row drained to the host mid-scan; the barrier
    #    before the clock stops makes the host-side drain part of the
    #    timed cost (that's the price being measured)
    stream = telemetry.StreamSpec(registry=registry)
    stream_window = telemetry.make_window_runner(
        cfg, proto, registry, window, step=step, stream=stream)

    def stream_run(world, ring, timed):
        t0 = time.perf_counter()
        world, ring = stream_window(world, ring)
        _rows, ring = telemetry.flush(ring, registry)
        jax.effects_barrier()
        dt = time.perf_counter() - t0
        return world, ring, (dt if timed else None)

    sring = telemetry.make_ring(registry, window)
    ws, sring, _ = stream_run(world0, sring, timed=False)
    stream_secs = []
    for _ in range(args.windows):
        ws, sring, dt = stream_run(ws, sring, timed=True)
        stream_secs.append(dt)

    # -- plain arm: identical schedule from the same initial world
    wp = plain_window(world0)
    int(wp.rnd)                                   # sync (warmup/compile)
    plain_secs = []
    for _ in range(args.windows):
        t0 = time.perf_counter()
        wp = plain_window(wp)
        int(wp.rnd)                               # scalar readback = sync
        plain_secs.append(time.perf_counter() - t0)

    jsonl.close()
    prom.close()

    plain_rps = window / statistics.median(plain_secs)
    telem_rps = window / statistics.median(telem_secs)
    flight_rps = window / statistics.median(flight_secs)
    stream_rps = window / statistics.median(stream_secs)
    tracer_rps = window / statistics.median(trace_secs)
    overhead = (plain_rps - telem_rps) / plain_rps * 100.0
    flight_overhead = (telem_rps - flight_rps) / telem_rps * 100.0
    stream_overhead = (telem_rps - stream_rps) / telem_rps * 100.0
    tracer_overhead = (flight_rps - tracer_rps) / flight_rps * 100.0
    summary = {
        "metric": f"telemetry overhead @ HyParView N={n}, window={window}",
        "n": n, "window": window, "timed_windows": args.windows,
        "plain_rounds_per_sec": round(plain_rps, 2),
        "telemetry_rounds_per_sec": round(telem_rps, 2),
        "overhead_pct": round(overhead, 2),
        "flight_rounds_per_sec": round(flight_rps, 2),
        "flight_overhead_pct": round(flight_overhead, 2),
        "flight_cap": args.flight_cap,
        "flight_entries": flight_entries_total,
        "flight_overflow": flight_overflow_total,
        "tracer_rounds_per_sec": round(tracer_rps, 2),
        "tracer_overhead_pct": round(tracer_overhead, 2),
        "trace_cap": args.trace_cap,
        "trace_events": trace_events_total,
        "trace_overflow": trace_overflow_total,
        "stream_rounds_per_sec": round(stream_rps, 2),
        "stream_overhead_pct": round(stream_overhead, 2),
        "stream_rows": stream.rows_streamed,
        "stream_last_round": stream.last_round,
        "msgs_delivered_total": sum(r["msgs_delivered"] for r in all_rows),
        "out_dropped_total": sum(r["out_dropped"] for r in all_rows),
        "isolated_max": max(r["isolated"] for r in all_rows),
        "isolated_last": all_rows[-1]["isolated"],
        "rounds_per_sec_last_window": round(
            timeline.windows[-1]["rounds_per_sec"], 2),
        "device": jax.devices()[0].platform,
    }
    print(json.dumps(summary))

    # unified bench ledger (ISSUE 18): one BenchRow per telemetry arm,
    # so the overhead trend is queryable next to every other suite; the
    # stdout summary and BENCH_telemetry.* artifacts stay unchanged
    from partisan_tpu.telemetry import benchplane
    calib = benchplane.calibrate()
    rounds = window * args.windows
    benchplane.append_rows_nonfatal(
        [benchplane.make_row(
            "bench_telemetry", arm,
            config={"window": window, "windows": args.windows,
                    "flight_cap": args.flight_cap,
                    "trace_cap": args.trace_cap},
            n_nodes=n, rounds=rounds, rounds_per_sec=rps,
            wall_s=round(rounds / rps, 4) if rps else None,
            calibration=calib, metrics={"overhead_pct": ovh})
         for arm, rps, ovh in [
             ("plain", plain_rps, None),
             ("telemetry", telem_rps, summary["overhead_pct"]),
             ("flight", flight_rps, summary["flight_overhead_pct"]),
             ("stream", stream_rps, summary["stream_overhead_pct"]),
             ("tracer", tracer_rps, summary["tracer_overhead_pct"])]],
        os.environ.get("PARTISAN_BENCH_LEDGER"))


if __name__ == "__main__":
    main()
