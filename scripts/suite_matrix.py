"""Suite-matrix parity driver — the reference's Common Test group matrix
(test/partisan_SUITE.erl:121-308: groups x managers x feature flags)
enumerated as parameterized configs and driven through BOTH the
in-process engine and the Erlang port bridge, emitting one parity row
per (group, test, path) into ``suite_matrix.csv``:

    group,test,manager,path,result,detail

``result`` is pass / fail / skipped; skipped rows carry the reason a
group has no simulator analog (TLS handshakes, disterl, BEAM
binary-heap tricks — transport-level machinery the round-synchronous
simulator replaces wholesale, SURVEY §7.4).

Usage: python scripts/suite_matrix.py [--out suite_matrix.csv]
       [--only SUBSTR] [--engine-only]
"""

from __future__ import annotations

import argparse
import csv
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the multichip/dataplane rows need the 8-device virtual CPU mesh; the
# flag must be in the environment BEFORE the first backend init
# (tests/conftest.py applies the same setup)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# parity cases belong on the CPU backend (the real chip stays free for
# bench.py, and a tunnel-worker restart mid-run poisons every later
# case).  The env var alone does NOT select CPU on this image — only
# config.update does (see the tpu-tunnel measurement notes).
jax.config.update("jax_platforms", "cpu")
# share the repo-local persistent compilation cache with the tests and
# scripts: the explorer parity case reuses the vmapped HyParView program
# (minutes cold, seconds warm) that tests/test_explorer.py compiles
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import partisan_tpu as pt  # noqa: E402
from partisan_tpu import peer_service as ps  # noqa: E402
from partisan_tpu.models.dataplane import DataPlane  # noqa: E402
from partisan_tpu.models.stack import Stacked  # noqa: E402
from partisan_tpu.ops import graph  # noqa: E402
from partisan_tpu.verify import faults  # noqa: E402


# ----------------------------------------------------------------- helpers

def _manager(name, cfg):
    if name == "full":
        from partisan_tpu.models.full_membership import FullMembership
        return FullMembership(cfg)
    if name == "hyparview":
        from partisan_tpu.models.hyparview import HyParView
        return HyParView(cfg)
    if name == "scamp_v1":
        from partisan_tpu.models.scamp import ScampV1
        return ScampV1(cfg)
    if name == "scamp_v2":
        from partisan_tpu.models.scamp import ScampV2
        return ScampV2(cfg)
    if name == "static":
        from partisan_tpu.models.managers import StaticManager
        return StaticManager(cfg)
    if name == "client_server":
        from partisan_tpu.models.managers import ClientServerManager
        return ClientServerManager(cfg)
    raise ValueError(name)


def _cluster(cfg, proto, rounds=20, pairs=None, **step_kw):
    world = pt.init_world(cfg, proto)
    world = ps.cluster(world, proto,
                       pairs or [(i, 0) for i in range(1, cfg.n_nodes)])
    step = pt.make_step(cfg, proto, donate=False, **step_kw)
    for _ in range(rounds):
        world, m = step(world)
    return world, step


def _with_dataplane(mgr_name, cfg, rounds=20):
    proto = Stacked(_manager(mgr_name, cfg), DataPlane(cfg))
    world, step = _cluster(cfg, proto, rounds=rounds)
    return proto, world, step


def _assert_members_converged(world, proto, n):
    masks = np.asarray(
        [np.asarray(ps.members(world, proto, i)) for i in range(n)])
    assert masks.all(), f"membership not converged:\n{masks.sum(axis=1)}"


def _forward_roundtrip(proto, world, step, n, rounds=4, **opts):
    """check_forward_message (partisan_SUITE.erl:1955): a distinct value
    into EVERY node's store."""
    world = ps.forward_batch(world, proto, [
        {"src": (i + 1) % n, "dst": i, "server_ref": i,
         "payload": [1000 + i], **opts} for i in range(n)])
    for _ in range(rounds):
        world, _ = step(world)
    for i in range(n):
        recs, _, _ = ps.receive_messages(world, proto, i)
        got = [(s, r, p[0]) for s, r, p in recs]
        assert ((i + 1) % n, i, 1000 + i) in got, (i, recs)
    return world


# ------------------------------------------------------------ engine cases
# Each case mirrors one (group, test) cell of the reference matrix; the
# docstring cites the reference test it stands for.

def basic_test(manager="full", **cfg_kw):
    """basic_test (:1399): cluster forms, members agree, a value
    round-trips into every node's store."""
    n = 4
    cfg = pt.Config(n_nodes=n, inbox_cap=16, periodic_interval=2, **cfg_kw)
    proto, world, step = _with_dataplane(manager, cfg)
    _assert_members_converged(world, proto, n)
    _forward_roundtrip(proto, world, step, n)


def leave_test(self_leave=False):
    """leave_test / self_leave_test: departure propagates to every
    member's view."""
    n = 4
    cfg = pt.Config(n_nodes=n, inbox_cap=16, periodic_interval=2)
    from partisan_tpu.models.full_membership import FullMembership
    proto = FullMembership(cfg)
    world, step = _cluster(cfg, proto)
    _assert_members_converged(world, proto, n)
    world = ps.leave(world, proto, 3 if self_leave else 0,
                     None if self_leave else 3)
    for _ in range(12):
        world, _ = step(world)
    for i in range(3):
        mask = np.asarray(ps.members(world, proto, i))
        assert not mask[3], f"node {i} still lists the departed node"


def on_down_test():
    """on_down_test: membership-change callbacks fire on departure
    (events.PeerServiceEvents — partisan_peer_service_events.erl:59-81)."""
    from partisan_tpu import events
    from partisan_tpu.models.full_membership import FullMembership
    n = 4
    cfg = pt.Config(n_nodes=n, inbox_cap=16, periodic_interval=2)
    proto = FullMembership(cfg)
    world, step = _cluster(cfg, proto)
    ev = events.PeerServiceEvents(proto)
    fired = []
    ev.add_sup_callback(lambda node, mask: fired.append((node, mask.copy())))
    ev.update(world)
    world = ps.leave(world, proto, 0, 3)
    for _ in range(12):
        world, _ = step(world)
        ev.update(world)
    assert any(not mask[3] for _, mask in fired), \
        "no callback observed node 3 going down"


def rpc_test(**cfg_kw):
    """rpc_test (:813): a call ships, applies remotely, fulfils the
    caller's promise."""
    from partisan_tpu.qos.rpc import Rpc
    cfg = pt.Config(n_nodes=4, inbox_cap=8, **cfg_kw)
    proto = Rpc(cfg, fns=(lambda x: x * 2,))
    world = pt.init_world(cfg, proto)
    step = pt.make_step(cfg, proto, donate=False)
    world = ps.send_ctl(world, proto, 1, "ctl_call", peer=2, fn=0, arg=21)
    for _ in range(4):
        world, _ = step(world)
    assert bool(world.state.prom_done[1].any())
    assert 42 in np.asarray(world.state.prom_result[1])


def client_server_manager_test():
    """client_server_manager_test: clients attach to servers only."""
    from partisan_tpu.models.managers import ClientServerManager
    n = 6
    cfg = pt.Config(n_nodes=n, inbox_cap=16)
    proto = ClientServerManager(cfg, n_servers=2)
    world, step = _cluster(cfg, proto, pairs=[(i, i % 2) for i in range(2, n)])
    for c in range(2, n):
        mask = np.asarray(ps.members(world, proto, c))
        assert mask[:2].any(), f"client {c} reached no server: {mask}"
        others = [j for j in range(2, n) if j != c]
        assert not mask[others].any(), \
            f"client {c} linked to other clients: {mask}"


def rejoin_test():
    """rejoin_test: leave then join again converges."""
    from partisan_tpu.models.full_membership import FullMembership
    n = 4
    cfg = pt.Config(n_nodes=n, inbox_cap=16, periodic_interval=2)
    proto = FullMembership(cfg)
    world, step = _cluster(cfg, proto)
    world = ps.leave(world, proto, 3)
    for _ in range(10):
        world, _ = step(world)
    world = ps.join(world, proto, 3, 0)
    for _ in range(14):
        world, _ = step(world)
    _assert_members_converged(world, proto, n)


def transform_test():
    """transform_test: an imperatively-written (send-style) protocol runs
    on the engine contract (partisan_transform.erl analog)."""
    from partisan_tpu.transform import transformed
    from partisan_tpu.engine import ProtocolBase

    class Relay(transformed(ProtocolBase)):
        msg_types = ("token", "ctl_seed")
        emit_cap = 1

        def __init__(self, cfg):
            self.cfg = cfg
            self.data_spec = {"payload": ((), jnp.int32),
                              "peer": ((), jnp.int32)}

        def init(self, cfg, key):
            return jnp.zeros((cfg.n_nodes,), jnp.int32)

        def handle_token(self, cfg, me, row, m, key, send):
            nxt = (me + 1) % cfg.n_nodes
            send(jnp.where(m.data["payload"] > 0, nxt, -1), "token",
                 payload=m.data["payload"] - 1)
            return row + 1

        def handle_ctl_seed(self, cfg, me, row, m, key, send):
            send(me, "token", payload=m.data["payload"])
            return row

    cfg = pt.Config(n_nodes=4, inbox_cap=4)
    proto = Relay(cfg)
    world = pt.init_world(cfg, proto)
    step = pt.make_step(cfg, proto, donate=False)
    world = ps.send_ctl(world, proto, 0, "ctl_seed", payload=8)
    for _ in range(10):
        world, _ = step(world)
    assert int(np.asarray(world.state).sum()) == 9  # 8 hops + seed


def otp_test():
    """otp_test (:1261): a gen_server call over the overlay replies."""
    from partisan_tpu import otp

    class Doubler(otp.GenServer):
        def server_call(self, cfg, me, row, req, key):
            return row, req * 2

    cfg = pt.Config(n_nodes=4, inbox_cap=8)
    proto = Doubler(cfg)
    world = pt.init_world(cfg, proto)
    step = pt.make_step(cfg, proto, donate=False)
    world = ps.send_ctl(world, proto, 1, "ctl_call", peer=2,
                        req=jnp.asarray([21, 0], jnp.int32), timeout=10)
    for _ in range(4):
        world, _ = step(world)
    assert bool(world.state.call_done[1][0])
    assert int(world.state.call_reply[1][0][0]) == 42


def connectivity_test(manager, n=16, rounds=40):
    """connectivity_test (:1214): every node reaches every other over the
    overlay graph."""
    cfg = pt.Config(n_nodes=n, inbox_cap=16, periodic_interval=3)
    proto = _manager(manager, cfg)
    world, step = _cluster(cfg, proto, rounds=rounds)
    views = getattr(world.state, "active", None)
    if views is None:
        views = getattr(world.state, "partial", None)
    if views is not None:
        adj = graph.adjacency_from_views(views, n)
    else:
        masks = jnp.stack([ps.members(world, proto, i) for i in range(n)])
        adj = masks & ~jnp.eye(n, dtype=bool)
    assert bool(graph.is_connected(adj)), f"{manager} overlay disconnected"


def gossip_test(manager, n=8, rounds=24):
    """gossip_test (:1138): direct-mail broadcast (demers_direct_mail
    over the manager) delivers to every member."""
    cfg = pt.Config(n_nodes=n, inbox_cap=16, periodic_interval=3)
    proto = Stacked(_manager(manager, cfg), DataPlane(cfg, store_cap=8))
    world, step = _cluster(cfg, proto, rounds=rounds)
    # direct mail: node 0 sends the payload to every node
    world = ps.forward_batch(world, proto, [
        {"src": 0, "dst": i, "server_ref": 1, "payload": [777]}
        for i in range(1, n)])
    for _ in range(4):
        world, _ = step(world)
    for i in range(1, n):
        recs, _, _ = ps.receive_messages(world, proto, i)
        assert (0, 1, [777, 0, 0, 0]) in recs, (i, recs)


def ack_test():
    """ack_test (:573): acked messages survive omission faults via
    retransmission; outstanding drains to zero."""
    n = 4
    cfg = pt.Config(n_nodes=n, inbox_cap=16, periodic_interval=2)
    from partisan_tpu.models.full_membership import FullMembership
    proto = Stacked(FullMembership(cfg), DataPlane(cfg))
    fwd_typ = proto.typ("fwd")

    def drop_early_fwds(m, rnd):  # omission fault: fwds lost before r12
        return m.replace(valid=m.valid & ~((m.typ == fwd_typ) & (rnd < 12)))

    world = pt.init_world(cfg, proto)
    world = ps.cluster(world, proto, [(i, 0) for i in range(1, n)])
    step = pt.make_step(cfg, proto, donate=False,
                        interpose_send=drop_early_fwds)
    for _ in range(8):
        world, _ = step(world)
    world = ps.forward_message(world, proto, 1, 3, server_ref=9,
                               payload=[55], ack=True)
    for _ in range(12):
        world, _ = step(world)
    recs, _, _ = ps.receive_messages(world, proto, 3)
    assert any(r == (1, 9, [55, 0, 0, 0]) for r in recs), recs
    assert int(world.state.upper.out_valid[1].sum()) == 0


def causal_test():
    """causal_test (:402): delivery respects causal order under wire
    reordering (causality_backend)."""
    from partisan_tpu.qos.causal import CausalDelivery
    cfg = pt.Config(n_nodes=4, inbox_cap=8)
    proto = CausalDelivery(cfg)
    world = pt.init_world(cfg, proto)
    step = pt.make_step(cfg, proto, donate=False, randomize_delivery=False)
    # three sends 0 -> 1 whose wire delays REVERSE arrival order
    for k, d in ((1, 4), (2, 2), (3, 0)):
        world = ps.send_ctl(world, proto, 0, "ctl_csend", peer=1,
                            payload=k, cdelay=d)
        world, _ = step(world)
    for _ in range(10):
        world, _ = step(world)
    log = np.asarray(world.state.log[1])
    assert int(world.state.log_n[1]) == 3
    assert list(log[:3]) == [1, 2, 3], f"causal order violated: {log[:3]}"


def interposition_test(kind):
    """forward/receive/forward_delay interposition tests: drop or delay
    hooks between emit and route (pluggable :51-58, 640-667)."""
    n = 4
    cfg = pt.Config(n_nodes=n, inbox_cap=16, periodic_interval=2)
    from partisan_tpu.models.full_membership import FullMembership
    proto = Stacked(FullMembership(cfg), DataPlane(cfg))
    fwd_typ = proto.typ("fwd")

    if kind == "forward":
        hook = {"interpose_send": lambda m, rnd: m.replace(
            valid=m.valid & ~((m.typ == fwd_typ) & (m.dst == 2)))}
    elif kind == "receive":
        hook = {"interpose_recv": lambda m, rnd: m.replace(
            valid=m.valid & ~((m.typ == fwd_typ) & (m.dst == 2)))}
    else:  # forward_delay: the '$delay' verb
        hook = {"interpose_send": lambda m, rnd: m.replace(
            delay=jnp.where((m.typ == fwd_typ) & (m.dst == 2),
                            jnp.maximum(m.delay, 5), m.delay))}

    world = pt.init_world(cfg, proto)
    world = ps.cluster(world, proto, [(i, 0) for i in range(1, n)])
    step = pt.make_step(cfg, proto, donate=False, **hook)
    for _ in range(8):
        world, _ = step(world)
    world = ps.forward_message(world, proto, 0, 2, server_ref=1,
                               payload=[5])
    world = ps.forward_message(world, proto, 0, 3, server_ref=1,
                               payload=[6])
    for _ in range(3):
        world, _ = step(world)
    recs3, _, _ = ps.receive_messages(world, proto, 3)
    assert recs3 == [(0, 1, [6, 0, 0, 0])]  # untargeted node unaffected
    recs2, _, _ = ps.receive_messages(world, proto, 2)
    if kind in ("forward", "receive"):
        assert recs2 == [], recs2            # dropped
    else:
        assert recs2 == [], recs2            # delayed: not yet...
        for _ in range(5):
            world, _ = step(world)
        recs2, _, _ = ps.receive_messages(world, proto, 2)
        assert recs2 == [(0, 1, [5, 0, 0, 0])]  # ...but arrives later


def delay_test(field):
    """with_ingress/egress_delay (server :85-90, client :88-93): the
    config knob for the given side postpones every delivery by that many
    rounds (in the round-synchronous engine both knobs become rounds in
    flight — Config docstring)."""
    n = 4
    cfg = pt.Config(n_nodes=n, inbox_cap=16, **{field + "_delay": 4})
    from partisan_tpu.models.full_membership import FullMembership
    proto = Stacked(FullMembership(cfg), DataPlane(cfg))
    world = pt.init_world(cfg, proto)
    step = pt.make_step(cfg, proto, donate=False)
    world = ps.forward_message(world, proto, 0, 2, server_ref=1,
                               payload=[9])
    # undelayed arrival would be round 2 (ctl hop + fwd hop); the knob
    # adds 4 more
    for _ in range(4):
        world, _ = step(world)
    assert ps.receive_messages(world, proto, 2)[0] == []
    for _ in range(4):
        world, _ = step(world)
    assert ps.receive_messages(world, proto, 2)[0] == [(0, 1, [9, 0, 0, 0])]


def channels_test(channels, monotonic=(), rpc_on_channel=False):
    """with_channels / with_no_channels / with_monotonic_channels:
    basic_test under the channel config; monotonic channels elide stale
    sends (peer_connection :82-100)."""
    basic_test(channels=tuple(channels), monotonic_channels=tuple(monotonic))
    if rpc_on_channel:
        rpc_test(channels=tuple(channels))


def parallelism_test():
    """with_parallelism: k connection lanes per edge (partisan.hrl:16)."""
    basic_test(parallelism=4)


def partition_key_test():
    """with_partition_key: keyed messages ride a deterministic lane
    (dispatch_pid, partisan_util.erl:190-195)."""
    n = 4
    cfg = pt.Config(n_nodes=n, inbox_cap=16, parallelism=4,
                    periodic_interval=2)
    proto, world, step = _with_dataplane("full", cfg)
    _forward_roundtrip(proto, world, step, n, partition_key=3)


def sync_join_test():
    """with_sync_join: join blocks until fully connected
    (pluggable :1461-1480)."""
    from partisan_tpu.models.full_membership import FullMembership
    cfg = pt.Config(n_nodes=4, inbox_cap=8, periodic_interval=2)
    proto = FullMembership(cfg)
    world = pt.init_world(cfg, proto)
    step = pt.make_step(cfg, proto, donate=False)
    world, rounds = ps.sync_join(world, proto, 1, 0, step)
    assert rounds >= 1


def broadcast_test():
    """with_broadcast (hyparview_manager_high_active_test under
    broadcast): plumtree over hyparview delivers to all."""
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.models.plumtree import Plumtree
    n = 16
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5)
    proto = Stacked(HyParView(cfg), Plumtree(cfg, n_keys=1))
    world, step = _cluster(cfg, proto, rounds=20)
    world = ps.send_ctl(world, proto, 0, "ctl_pt_broadcast",
                        pt_key=0, pt_val=42)
    for _ in range(20):
        world, _ = step(world)
    vals = np.asarray(world.state.upper.val[:, 0])
    assert (vals == 42).all(), f"broadcast incomplete: {(vals == 42).sum()}/{n}"


def hyparview_partition_test():
    """hyparview_manager_partition_test (:1586): a partition splits the
    overlay; healing reconnects it."""
    from partisan_tpu.models.hyparview import HyParView
    n = 16
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5)
    proto = HyParView(cfg)
    world, step = _cluster(cfg, proto, rounds=20)
    world = faults.inject_partition(world, [list(range(8)),
                                            list(range(8, 16))])
    for _ in range(10):
        world, _ = step(world)
    world = faults.resolve_partition(world)
    for _ in range(30):
        world, _ = step(world)
    adj = graph.adjacency_from_views(world.state.active, n)
    assert bool(graph.is_connected(adj)), "overlay did not heal"


def hyparview_high_active_test():
    """hyparview_manager_high_active_test (:1706): connectivity and view
    symmetry at N past max_active."""
    connectivity_test("hyparview", n=24, rounds=40)


def hyparview_high_client_test():
    """hyparview_manager_high_client_test: many clients on few servers."""
    client_server_manager_test()


def sharded_dataplane_parity_test():
    """ISSUE 2 tentpole contract: 20 rounds of HyParView through the
    explicit shard_map dataplane (parallel/dataplane.py) on the
    8-device CPU mesh bit-match the unsharded engine step — metrics and
    state."""
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.parallel import make_mesh
    from partisan_tpu.parallel.dataplane import (
        make_sharded_step, place_sharded_world, sharded_out_cap)
    n = 64
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5)
    proto = HyParView(cfg)
    mesh = make_mesh(n_devices=8)
    pairs = [(i, i - 1) for i in range(1, n)]
    w = ps.cluster(pt.init_world(cfg, proto), proto, pairs, stagger=16)
    step = pt.make_step(cfg, proto, donate=False)
    w2 = ps.cluster(
        pt.init_world(cfg, proto,
                      out_cap=sharded_out_cap(cfg, proto, 8)),
        proto, pairs, stagger=16)
    w2 = place_sharded_world(w2, cfg, mesh)
    sstep = make_sharded_step(cfg, proto, mesh, donate=False)
    for _ in range(20):
        w, mp = step(w)
        w2, msh = sstep(w2)
        assert all(int(msh[k]) == int(v) for k, v in mp.items()), \
            (mp, msh)
        assert int(msh["xshard_dropped"]) == 0
    for lp, lsh in zip(jax.tree_util.tree_leaves(w.state),
                       jax.tree_util.tree_leaves(w2.state)):
        assert (np.asarray(lp) == np.asarray(lsh)).all()


def collective_budget_test():
    """ISSUE 2 comms gate: the compiled sharded round carries exactly
    one all_to_all + one psum — never an all-gather."""
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.parallel import make_mesh
    from partisan_tpu.parallel.dataplane import (init_sharded_world,
                                                 make_sharded_step)
    from partisan_tpu.parallel.mesh import assert_collective_budget
    cfg = pt.Config(n_nodes=64, inbox_cap=16, shuffle_interval=5)
    proto = HyParView(cfg)
    mesh = make_mesh(n_devices=8)
    w = init_sharded_world(cfg, proto, mesh)
    comp = make_sharded_step(cfg, proto, mesh,
                             donate=False).lower(w).compile()
    st = assert_collective_budget(comp, max_collectives=2,
                                  max_bytes=32 * 1024 * 1024,
                                  forbid=("all-gather",))
    assert st["counts"]["all-to-all"] == 1


def scamp_stagger_equivalence_test():
    """ISSUE 2 cadence: dense-SCAMP staggered at k=1 IS the every-round
    program (bit-equal), and chunked k=5 launches match single."""
    from partisan_tpu.models.scamp_dense import (
        dense_scamp_init, run_dense_scamp, run_dense_scamp_staggered)
    cfg = pt.Config(n_nodes=64, seed=4)
    a = run_dense_scamp(dense_scamp_init(cfg), 20, cfg, 0.02)
    b = run_dense_scamp_staggered(dense_scamp_init(cfg), 20, cfg,
                                  0.02, 1)
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        assert (np.asarray(la) == np.asarray(lb)).all()


def plumtree_lazy_equivalence_test():
    """ISSUE 2 cadence: the plumtree lazy cadence at k=1 equals the
    full-broadcast-every-round program bit-for-bit."""
    from partisan_tpu.models.hyparview_dense import dense_init, run_dense
    from partisan_tpu.models.plumtree_dense import (
        pt_dense_init, run_pt_dense_staggered)
    cfg = pt.Config(n_nodes=64, seed=3)
    hv = run_dense(dense_init(cfg), 60, cfg)
    p0 = pt_dense_init(cfg)
    a = run_pt_dense_staggered(hv, p0, 4, cfg, 0.01, 0, 1, True)
    b = run_pt_dense_staggered(hv, p0, 4, cfg, 0.01, 0, 1, False)
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        assert (np.asarray(la) == np.asarray(lb)).all()


def flight_recorder_parity_test():
    """ISSUE 3 tentpole contract: the windowed in-scan flight recorder
    (one device transfer per window) produces the entry-for-entry
    identical TraceEntry stream to the legacy per-round
    ``capture_wire=True`` path, losslessly."""
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.verify import TraceRecorder
    n = 64
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5)
    proto = HyParView(cfg)
    pairs = [(i, i - 1) for i in range(1, n)]
    w = ps.cluster(pt.init_world(cfg, proto), proto, pairs, stagger=16)
    legacy = TraceRecorder(cfg, proto)
    legacy.run(w, 20)
    w2 = ps.cluster(pt.init_world(cfg, proto), proto, pairs, stagger=16)
    fast = TraceRecorder(cfg, proto)
    fast.run_windowed(w2, 20, window=10)
    assert fast.flight_overflow == 0
    assert legacy.entries and fast.entries == legacy.entries


def dataplane_flight_telemetry_test():
    """ISSUE 3 dataplane coverage: per-shard flight rings through the
    shard_map round multiset-match the unsharded trace, and the
    asserted 2-collective budget holds with the recorder ON."""
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.parallel import make_mesh
    from partisan_tpu.parallel.dataplane import (
        make_sharded_step, place_sharded_world, sharded_out_cap)
    from partisan_tpu.parallel.mesh import assert_collective_budget
    from partisan_tpu.telemetry.flight import (
        FlightSpec, flight_entries, flight_flush, make_flight_ring,
        place_flight_ring)
    from partisan_tpu.verify import TraceRecorder
    n, rounds = 64, 10
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5)
    proto = HyParView(cfg)
    pairs = [(i, i - 1) for i in range(1, n)]
    rec = TraceRecorder(cfg, proto)
    rec.run_windowed(
        ps.cluster(pt.init_world(cfg, proto), proto, pairs, stagger=16),
        rounds, window=rounds)
    mesh = make_mesh(n_devices=8)
    out_cap = sharded_out_cap(cfg, proto, 8)
    w = ps.cluster(pt.init_world(cfg, proto, out_cap=out_cap), proto,
                   pairs, stagger=16)
    w = place_sharded_world(w, cfg, mesh)
    spec = FlightSpec(window=rounds, cap=out_cap)
    step = make_sharded_step(cfg, proto, mesh, donate=False,
                             flight=spec)
    ring = place_flight_ring(make_flight_ring(spec, n_shards=8), mesh)
    comp = step.lower(w, ring).compile()
    st = assert_collective_budget(comp, max_collectives=2,
                                  max_bytes=32 * 1024 * 1024,
                                  forbid=("all-gather",))
    assert st["counts"]["all-to-all"] == 1
    for _ in range(rounds):
        w, ring, _m = step(w, ring)
    rows, overflow, _ = flight_flush(ring)
    got = flight_entries(rows)
    assert overflow == 0
    key = lambda e: (e.rnd, e.src, e.dst, e.typ, e.channel, e.hash)
    assert sorted(map(key, got)) == sorted(map(key, rec.entries))


def chaos_parity_test():
    """ISSUE 4 tentpole contract: the SAME compiled ChaosSchedule
    (crash + partition + heal + recover mid-run, plus message-level
    drop/delay/duplicate events) over HyParView through the shard_map
    dataplane bit-matches the unsharded chaos run — states, fault
    planes, metrics AND the chaos counters — with the 2-collective
    budget unchanged."""
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.parallel import make_mesh
    from partisan_tpu.parallel.dataplane import (
        make_sharded_step, place_sharded_world, sharded_out_cap)
    from partisan_tpu.parallel.mesh import assert_collective_budget
    from partisan_tpu.verify.chaos import ChaosSchedule
    n, rounds = 64, 30
    sched = (ChaosSchedule()
             .crash(8, (3, 6))
             .partition(12, (0, 31), 1).partition(12, (32, 63), 2)
             .drop(14, dst=7, rounds=3)
             .delay(16, src=2, extra=2)
             .duplicate(18, copy_delay=1)
             .heal(22)
             .recover(24, (3, 6)))
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5)
    proto = HyParView(cfg)
    mesh = make_mesh(n_devices=8)
    pairs = [(i, i - 1) for i in range(1, n)]
    w = ps.cluster(pt.init_world(cfg, proto), proto, pairs, stagger=16)
    step = pt.make_step(cfg, proto, donate=False, chaos=sched)
    w2 = ps.cluster(
        pt.init_world(cfg, proto,
                      out_cap=sharded_out_cap(cfg, proto, 8)),
        proto, pairs, stagger=16)
    w2 = place_sharded_world(w2, cfg, mesh)
    sstep = make_sharded_step(cfg, proto, mesh, donate=False,
                              chaos=sched)
    st = assert_collective_budget(
        sstep.lower(w2).compile(), max_collectives=2,
        max_bytes=32 * 1024 * 1024, forbid=("all-gather",))
    assert st["counts"]["all-to-all"] == 1
    for _ in range(rounds):
        w, mp = step(w)
        w2, msh = sstep(w2)
        assert all(int(msh[k]) == int(v) for k, v in mp.items()), \
            (mp, msh)
    for lp, lsh in zip(jax.tree_util.tree_leaves((w.state, w.alive,
                                                  w.partition)),
                       jax.tree_util.tree_leaves((w2.state, w2.alive,
                                                  w2.partition))):
        assert (np.asarray(lp) == np.asarray(lsh)).all()


def chaos_soak_smoke():
    """ISSUE 4 campaign smoke: one tiny chaos_soak cell (lossy_combo,
    N=64) must report convergence-after-heal and write its JSONL row."""
    import importlib.util
    import tempfile
    spec = importlib.util.spec_from_file_location(
        "chaos_soak", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "chaos_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    with tempfile.TemporaryDirectory() as td:
        row = soak.run_cell(n=64, rounds=60, seed=1, mix="lossy_combo",
                            window=20, heal_margin=25, flight_cap=2048,
                            postmortem_dir=td)
        assert row["converged"], row
        assert row["postmortem"] is None, row


def latency_parity_test():
    """ISSUE 8 tentpole contract: the device-side latency histogram of
    a 30-round closed-loop RPC cell (N=64) bit-matches a host observer
    that recomputes every sample from the reply wire (the identity
    server echoes the birth round), and the 8-device sharded run is
    bit-identical to the unsharded one inside the 2-collective budget.
    Same program shapes as tests/test_workload.py, shared via the
    persistent compile cache."""
    from partisan_tpu.parallel.dataplane import (make_sharded_step,
                                                 place_world)
    from partisan_tpu.parallel.mesh import (assert_collective_budget,
                                            make_mesh)
    from partisan_tpu.workload import arrivals, latency
    from partisan_tpu.workload.driver import WorkloadRpc
    cfg = pt.Config(n_nodes=64, inbox_cap=16, seed=5,
                    retransmit_interval=100, slo_deadline_rounds=4)
    proto = WorkloadRpc(cfg, promise_cap=8,
                        spec=arrivals.ArrivalSpec(
                            kind=arrivals.CLOSED, closed_target=2,
                            max_issue=4))
    rounds, reply_t = 30, proto.typ("rpc_reply")
    world = pt.init_world(cfg, proto)
    step = pt.make_step(cfg, proto, donate=False)
    seen, host_lats = set(), []
    for t in range(rounds):
        world, m = step(world)
        assert int(m["inbox_overflow"]) == 0
        if t == rounds - 1:
            break  # in-flight replies after the last step never deliver
        ms = world.msgs
        ok = np.asarray(ms.valid) & (np.asarray(ms.typ) == reply_t)
        dst, born = np.asarray(ms.dst), np.asarray(ms.born)
        ref = np.asarray(ms.data["ref"])
        res = np.asarray(ms.data["result"])
        for i in np.nonzero(ok)[0]:
            k = (int(dst[i]), int(ref[i]))
            if k not in seen:
                seen.add(k)
                host_lats.append(int(born[i]) + 1 + cfg.ingress_delay
                                 + cfg.egress_delay - int(res[i]))
    dev = np.asarray(jnp.sum(world.state.lat_hist, axis=0))
    assert len(host_lats) > 500
    assert (dev == latency.host_hist(host_lats)).all(), (dev, host_lats)
    # sharded twin: bit-identical histogram, budget held workload-on
    mesh = make_mesh()
    w2 = place_world(pt.init_world(cfg, proto), mesh)
    sstep = make_sharded_step(cfg, proto, mesh, donate=False)
    st = assert_collective_budget(
        sstep.lower(w2).compile(), max_collectives=2,
        max_bytes=32 * 1024 * 1024, forbid=("all-gather",))
    assert st["counts"]["all-to-all"] == 1
    for _ in range(rounds):
        w2, _ = sstep(w2)
    assert (np.asarray(jnp.sum(w2.state.lat_hist, axis=0)) == dev).all()


def load_suite_smoke():
    """ISSUE 8 bench-harness smoke: one tiny single-arm load_suite
    sweep through the real CLI — the window-delta measurement, knee
    fold and JSONL schema must hold end to end."""
    import importlib.util
    import json
    import tempfile
    spec = importlib.util.spec_from_file_location(
        "load_suite", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "load_suite.py"))
    ls = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ls)
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "bench.jsonl")
        # N=16 toy rows must not land in the committed BENCH_ledger
        # (trend_report groups by (suite, arm) — smoke rows would
        # corrupt the real load_suite series)
        prev = os.environ.get("PARTISAN_BENCH_LEDGER")
        os.environ["PARTISAN_BENCH_LEDGER"] = os.path.join(
            td, "ledger.jsonl")
        try:
            rc = ls.main(["--n", "16", "--rates", "1000", "--rounds",
                          "6", "--warm", "2", "--skip-sharded",
                          "--skip-shed", "--out", out])
        finally:
            if prev is None:
                os.environ.pop("PARTISAN_BENCH_LEDGER", None)
            else:
                os.environ["PARTISAN_BENCH_LEDGER"] = prev
        assert rc == 0
        with open(out) as f:
            rows = [json.loads(line) for line in f]
    assert rows[-1]["bench"] == "load_suite_summary"
    assert rows[0]["arm"] == "engine" and rows[0]["completions"] > 0


def dense_budget_test():
    """ISSUE 9 tentpole contract: every explicit-SPMD dense round
    (hyparview / scamp / plumtree, parallel/dense_dataplane.py)
    compiles to exactly ONE bucketed all-to-all + ONE metrics
    all-reduce and ZERO all-gathers on the 8-device mesh — the
    regression pin for the collective budget (the implicit GSPMD
    lowering of the same round emits 19 all-gathers).  Same program
    shapes as tests/test_dense_dataplane.py, shared via the persistent
    compile cache."""
    from partisan_tpu.parallel import dense_dataplane as dd
    from partisan_tpu.parallel.mesh import (assert_collective_budget,
                                            make_mesh)
    mesh = make_mesh(n_devices=8)
    budget = dict(max_collectives=3, max_bytes=64 << 20,
                  forbid=("all-gather",),
                  max_counts={"all-to-all": 1, "all-reduce": 2,
                              "collective-permute": 2})
    hv_cfg = pt.Config(n_nodes=256, shuffle_interval=4,
                       random_promotion_interval=2)
    sc_cfg = pt.Config(n_nodes=256)
    cases = (
        ("hyparview", hv_cfg, dd.sharded_dense_init, {}),
        ("scamp", sc_cfg, dd.sharded_scamp_init, {"churn": 0.01}),
        ("plumtree", hv_cfg, dd.sharded_pt_init,
         {"broadcast_interval": 5}),
    )
    for model, cfg, init, kw in cases:
        step = dd.make_sharded_dense_round(cfg, mesh, model=model, **kw)
        st = dd.place_sharded(init(cfg, 8), mesh)
        stats = assert_collective_budget(step.lower(st).compile(), **budget)
        assert stats["counts"]["all-gather"] == 0, model
        assert stats["counts"]["all-to-all"] == 1, model
        assert stats["counts"]["all-reduce"] == 1, model


def control_parity_test():
    """ISSUE 10 tentpole contract: the in-scan admission controller's
    setpoint trajectory bit-matches the plain-Python host twin replaying
    the same metric stream, the 8-device sharded trajectory is
    bit-identical to the unsharded one, the collective budget with
    controllers ON stays exactly {all-to-all: 1, all-reduce: 1,
    all-gather: 0}, and controllers OFF lowers the identical program.
    Same program shapes as tests/test_control.py, shared via the
    persistent compile cache."""
    from partisan_tpu.control import (ControlSpec, Controller,
                                      attach_plane, host_update_plane)
    from partisan_tpu.control.plane import host_init_plane
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.models.stack import Lifted
    from partisan_tpu.parallel import mesh as pmesh
    from partisan_tpu.parallel.dataplane import (make_sharded_step,
                                                 place_sharded_world,
                                                 sharded_out_cap)
    from partisan_tpu.workload import arrivals
    from partisan_tpu.workload.driver import AdaptiveWorkloadRpc
    cfg = pt.Config(n_nodes=16, inbox_cap=16, seed=3,
                    slo_deadline_rounds=8, shed_token_burst_milli=8000)
    drv = AdaptiveWorkloadRpc(
        cfg, promise_cap=8,
        spec=arrivals.ArrivalSpec(kind=arrivals.POISSON, max_issue=4),
        rate_milli=6000, shed_rate_milli=4000)
    proto = Stacked(HyParView(cfg), Lifted(drv))
    spec = ControlSpec((
        Controller(name="admit", metric="rpc_slo_violated",
                   actuator="wl.shed_rate_milli", kind="aimd",
                   init=4000, target_milli=0, sense=1, delta=True,
                   alpha_milli=400, add=200, mult_milli=900,
                   lo=1000, hi=8000),
    ))
    world = attach_plane(pt.init_world(cfg, proto), spec)
    step = pt.make_step(cfg, proto, donate=False, control=spec)
    traj, rows = [], []
    for _ in range(12):
        world, m = step(world)
        traj.append(int(m["ctl_admit__setpoint"]))
        rows.append({k: int(v) for k, v in m.items() if np.ndim(v) == 0})
    hp = host_init_plane(spec)
    for m, sp in zip(rows, traj):
        hp = host_update_plane(spec, hp, m)
        assert hp["setpoint"][0] == sp  # host twin bit-parity
    mesh = pmesh.make_mesh()
    ws = attach_plane(
        pt.init_world(cfg, proto,
                      out_cap=sharded_out_cap(cfg, proto, 8, None)), spec)
    ws = place_sharded_world(ws, cfg, mesh)
    sstep = make_sharded_step(cfg, proto, mesh, donate=False,
                              control=spec)
    straj = []
    for _ in range(12):
        ws, sm = sstep(ws)
        straj.append(int(sm["ctl_admit__setpoint"]))
    assert straj == traj  # sharded == unsharded, bit-identical
    st = pmesh.assert_collective_budget(
        sstep.lower(ws).compile(), max_collectives=2,
        max_bytes=32 * 1024 * 1024, forbid=("all-gather",))
    assert st["counts"]["all-to-all"] == 1
    assert st["counts"]["all-reduce"] == 1
    assert st["counts"].get("all-gather", 0) == 0
    w0 = pt.init_world(cfg, proto)
    s_off = pt.make_step(cfg, proto, donate=False)
    s_none = pt.make_step(cfg, proto, donate=False, control=None)
    assert s_off.lower(w0).as_text() == s_none.lower(w0).as_text()


def control_suite_smoke():
    """ISSUE 10 bench-harness smoke: one tiny control_suite cell
    through the real CLI — the admission static-vs-adaptive arms, the
    chaos retransmit arms and the JSONL schema must hold end to end
    (full benches live in scripts/control_suite.py ->
    BENCH_control.jsonl; the sharded budget is control_parity_test's
    pin, skipped here for wall time)."""
    import importlib.util
    import json
    import tempfile
    spec = importlib.util.spec_from_file_location(
        "control_suite", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "control_suite.py"))
    cs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cs)
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "bench.jsonl")
        rc = cs.main(["--smoke", "--skip-sharded", "--out", out])
        assert rc == 0
        with open(out) as f:
            rows = [json.loads(line) for line in f]
    summary = rows[-1]
    assert summary["bench"] == "control_suite_summary"
    arms = {r["arm"] for r in rows[:-1]}
    assert {"static", "adaptive", "chaos_fixed",
            "chaos_adaptive"} <= arms
    assert summary["chaos_equal_delivery"] is True
    assert summary["chaos_adaptive_retx"] < summary["chaos_fixed_retx"]


def dense_scale_smoke():
    """ISSUE 9 bench-harness smoke: one N=4096 window of the
    implicit-vs-explicit scale suite through the real CLI — both arms
    must run, report rounds/sec and carry their per-round collective
    tables in the JSONL schema (full sweeps live in
    scripts/dense_scale_suite.py -> BENCH_dense_scale.jsonl)."""
    import json
    import subprocess
    import tempfile
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "dense_scale_suite.py")
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "bench.jsonl")
        csvp = os.path.join(td, "results.csv")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PARTISAN_BENCH_LEDGER=os.path.join(td, "ledger.jsonl"))
        env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        rc = subprocess.run(
            [sys.executable, script, "--smoke", "--out", out,
             "--csv", csvp], env=env, timeout=560).returncode
        assert rc == 0
        with open(out) as f:
            rows = [json.loads(line) for line in f]
    arms = {r["arm"]: r for r in rows}
    assert set(arms) == {"implicit", "explicit"}
    for r in rows:
        assert "error" not in r, r
        assert r["rounds_per_sec"] > 0
    assert arms["explicit"]["collectives_per_round"].get("all-gather", 0) == 0
    assert arms["explicit"]["collectives_per_round"]["all-to-all"] == 1


def explorer_parity_test():
    """ISSUE 7 tentpole contract: a B=1 execution through the batched
    fault-space explorer (vmapped scan over a traced chaos table) is
    bit-identical to the static ``make_step(chaos=)`` path — per-round
    metrics with chaos counters, final state, fault planes and the
    valid message prefix — on 60-round HyParView under a schedule
    exercising every event kind.  Same program shapes as
    tests/test_explorer.py, shared via the persistent compile cache."""
    from partisan_tpu.verify.chaos import ChaosSchedule
    from partisan_tpu.verify.explorer import Explorer, SETUPS
    n, rounds = 16, 60
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5, seed=3)
    proto, world = SETUPS["hyparview_tree"](cfg)
    ex = Explorer(cfg, proto, n_rounds=rounds, n_events=10, batch=1,
                  world=world, heal_margin=12)
    sched = (ChaosSchedule().crash(8, (4, 7))
             .partition(10, (0, 7), 1).partition(10, (8, 15), 2)
             .drop(12, dst=3, rounds=5).drop_typ(13, typ=1, rounds=3)
             .delay(14, src=2, extra=2).duplicate(16)
             .heal(30).recover(32, (4, 7)))
    wf, metrics, _ = ex.run_batch_with_metrics([sched])
    step = pt.make_step(cfg, proto, donate=False, chaos=sched)
    w = world
    for r in range(rounds):
        w, m = step(w)
        for k, v in m.items():
            assert int(np.asarray(metrics[k])[0, r]) == int(v), (k, r)
    w0 = jax.tree_util.tree_map(lambda l: np.asarray(l)[0], wf)
    for lp, lb in zip(
            jax.tree_util.tree_leaves((w.state, w.alive, w.partition,
                                       w.keys, w.rnd)),
            jax.tree_util.tree_leaves((w0.state, w0.alive, w0.partition,
                                       w0.keys, w0.rnd))):
        assert (np.asarray(lp) == np.asarray(lb)).all()
    va, vb = w0.msgs.valid.astype(bool), np.asarray(w.msgs.valid)
    assert (va == vb).all()
    for name in ("src", "dst", "typ", "channel", "lane", "delay",
                 "born"):
        assert (getattr(w0.msgs, name)[va]
                == np.asarray(getattr(w.msgs, name))[vb]).all(), name


def explore_smoke():
    """ISSUE 7 campaign smoke: the batched explorer campaign
    (AckedDelivery phases, B=8) finds the planted dead-letter bug from
    a flight-trace frontier, shrinks it, and the written counterexample
    JSON replays — exit 0 and JSONL rows on disk."""
    import importlib.util
    import json as json_mod
    import tempfile
    spec = importlib.util.spec_from_file_location(
        "chaos_explore", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "chaos_explore.py"))
    explore = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(explore)
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "BENCH_explore.jsonl")
        rc = explore.main(["--smoke", "--out", out,
                           "--counterexample-dir", td,
                           "--postmortem-dir", td])
        assert rc == 0
        with open(out) as f:
            rows = [json_mod.loads(ln) for ln in f]
    phases = {r["phase"] for r in rows}
    assert {"frontier", "explore", "shrink", "bench"} <= phases
    sweep = next(r for r in rows if r["phase"] == "explore")
    assert sweep["counterexamples_found"] > 0
    shrink = next(r for r in rows if r["phase"] == "shrink")
    assert shrink["replay_reproduced"] is True
    assert shrink["shrunk_events"] <= 3
    bench = next(r for r in rows if r["phase"] == "bench")
    assert bench["batched_schedules_per_sec"] > 0
    assert bench["serial_schedules_per_sec"] > 0


def performance_test():
    """performance_test (:1029): the echo harness completes its streams
    (the full swept numbers live in scripts/perf_suite.py ->
    results.csv)."""
    from partisan_tpu.models.echo import Echo
    cfg = pt.Config(n_nodes=2, inbox_cap=8)
    proto = Echo(cfg, concurrency=2, size_words=8, total=10)
    world = pt.init_world(cfg, proto)
    step = pt.make_step(cfg, proto, donate=False)
    world = ps.send_ctl(world, proto, 0, "ctl_start", peer=0)
    for _ in range(30):
        world, _ = step(world)
    assert bool(proto.done(world))


# -------------------------------------------------------------- port cases
#
# Port rows drive the SAME CT contracts through a real port_server
# subprocess over the packet-4/ETF wire — the Erlang-facing path.  Two
# wall-time levers (VERDICT r2 missing #1 / weak #4):
#   * sessions POOL per config profile: cmd_start on a live process
#     resets the world, and identical shapes hit the process's jit cache,
#     so only the first row per profile pays the 30-90 s CPU compile;
#   * join storms and multi-step drives ship as ONE multi-command
#     {batch, [...]} frame (cmd_batch) instead of per-verb round-trips.

_POOL = {}


def _pc(profile):
    from partisan_tpu.bridge.client import PortClient
    pc = _POOL.get(profile)
    if pc is None or pc.proc.poll() is not None:
        pc = _POOL[profile] = PortClient()
    return pc


def _pool_close():
    for pc in _POOL.values():
        try:
            pc.stop()
        except Exception:  # noqa: BLE001
            pass
    _POOL.clear()


def _A(name):
    from partisan_tpu.bridge.etf import Atom
    return Atom(name)


def _port_join_all(pc, pairs):
    replies = pc.batch(*[( _A("join"), i, p) for i, p in pairs])
    assert all(r == _A("ok") for r in replies), replies


def port_basic_test(manager="full", profile=None, channel=None, **props):
    pc = _pc(profile or f"basic_{manager}_{sorted(props.items())}")
    assert pc.start(manager, n_nodes=4, periodic_interval=2,
                    **props) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, 4)])
    pc.advance(16)
    assert pc.members(0) == list(range(4))
    opts = {} if channel is None else {"channel": channel}
    for i in range(4):
        pc.forward((i + 1) % 4, i, i, [1000 + i], **opts)
    pc.advance(4)
    for i in range(4):
        recs, lost = pc.recv(i)
        assert lost == 0
        assert ((i + 1) % 4, i, [1000 + i, 0, 0, 0]) in recs, (i, recs)


def port_connectivity_test(manager, n=16, rounds=60, **props):
    pc = _pc(f"conn_{manager}_{n}")
    assert pc.start(manager, n_nodes=n, periodic_interval=3,
                    data_plane=False, **props) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, n)])
    pc.advance(rounds)
    h = pc.health()
    if manager == "full":
        assert h.get(_A("convergence"), 0) == 1.0, h
    elif manager == "hyparview":
        # healthy overlay = nobody isolated and views at least
        # min_active deep (the membership_check analog reachable
        # through the port's health surface)
        assert h.get(_A("isolated"), 1) == 0, h
        assert h.get(_A("mean_view"), 0) >= 3, h
    else:
        # SCAMP: view sizes scale ~(c+1)·ln N / fan-in, not min_active —
        # the right invariant is overlay connectivity (the reference's
        # connectivity_test digraph check, :1214)
        assert h.get(_A("isolated"), 1) == 0, h
        assert bool(graph.is_connected(_port_adjacency(pc, n))), \
            f"{manager} overlay disconnected through the port"


def _port_adjacency(pc, n):
    """all-pairs reachability over the port's members/1 surface — the
    digraph check of hyparview_membership_check (partisan_SUITE
    :2044-2109) driven through the bridge."""
    adj = np.zeros((n, n), bool)
    replies = pc.batch(*[(_A("members"), i) for i in range(n)])
    for i, r in enumerate(replies):
        ok, ids = r
        assert ok == _A("ok")
        for j in ids:
            adj[i, int(j)] = True
    return jnp.asarray(adj)


def port_hyparview_partition_test():
    """hyparview_manager_partition_test (:1586) through the port: split,
    heal, reconnect."""
    n = 16
    pc = _pc(f"conn_hyparview_{n}")
    assert pc.start("hyparview", n_nodes=n, shuffle_interval=5,
                    data_plane=False) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, n)])
    pc.advance(20)
    assert pc.call((_A("partition"),
                    [list(range(8)), list(range(8, 16))])) == _A("ok")
    pc.advance(10)
    assert pc.call((_A("resolve_partition"),)) == _A("ok")
    pc.advance(30)
    assert bool(graph.is_connected(_port_adjacency(pc, n))), \
        "overlay did not heal through the port path"


def port_hyparview_high_active_test():
    """hyparview_manager_high_active_test (:1706) through the port."""
    n = 24
    pc = _pc(f"conn_hyparview_{n}")
    assert pc.start("hyparview", n_nodes=n, shuffle_interval=5,
                    data_plane=False) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, n)])
    pc.advance(40)
    assert bool(graph.is_connected(_port_adjacency(pc, n)))


def port_causal_test():
    """causal_test (:402) through the port: three sends whose wire delays
    reverse arrival still deliver in causal order."""
    pc = _pc("causal4")
    assert pc.start("causal", n_nodes=4, inbox_cap=8) == _A("ok")
    for k, d in ((1, 4), (2, 2), (3, 0)):
        assert pc.csend(0, 1, k, delay=d) == _A("ok")
        pc.advance(1)
    pc.advance(10)
    log, total = pc.clog(1)
    assert total == 3 and log == [1, 2, 3], (log, total)


def port_monotonic_test():
    """with_monotonic_channels through the port: two same-round sends on
    a monotonic channel elide to the latest (peer_connection :82-100);
    the plain channel keeps both."""
    pc = _pc("full4mono")
    assert pc.start("full", n_nodes=4, periodic_interval=2,
                    channels=["undefined", "mono"],
                    monotonic_channels=["mono"]) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, 4)])
    pc.advance(10)
    pc.batch((_A("forward"), 0, 2, 1, [71], [(_A("channel"), 1)]),
             (_A("forward"), 0, 2, 1, [72], [(_A("channel"), 1)]),
             (_A("forward"), 0, 3, 1, [81], []),
             (_A("forward"), 0, 3, 1, [82], []))
    pc.advance(4)
    mono_recs, _ = pc.recv(2)
    assert mono_recs == [(0, 1, [72, 0, 0, 0])], mono_recs  # elided
    plain_recs, _ = pc.recv(3)
    assert len(plain_recs) == 2, plain_recs                 # both kept


def port_interposition_test(kind):
    """forward/receive/forward_delay interposition through the port's
    {interpose, ...} surface (pluggable add_*_interposition_fun
    :51-58)."""
    pc = _pc("full4")
    assert pc.start("full", n_nodes=4, periodic_interval=2) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, 4)])
    pc.advance(8)
    if kind == "forward":
        assert pc.interpose("send", "drop", typ="fwd", dst=2) == _A("ok")
    elif kind == "receive":
        assert pc.interpose("recv", "drop", typ="fwd", dst=2) == _A("ok")
    else:
        assert pc.interpose("send", "delay", typ="fwd", dst=2,
                            delay=5) == _A("ok")
    try:
        pc.forward(0, 2, 1, [5])
        pc.forward(0, 3, 1, [6])
        pc.advance(3)
        recs3, _ = pc.recv(3)
        assert recs3 == [(0, 1, [6, 0, 0, 0])], recs3
        recs2, _ = pc.recv(2)
        assert recs2 == [], recs2
        if kind == "forward_delay":
            pc.advance(5)
            recs2, _ = pc.recv(2)
            assert recs2 == [(0, 1, [5, 0, 0, 0])], recs2
    finally:
        pc.interpose("send" if kind != "receive" else "recv", "clear")


def port_broadcast_test():
    """with_broadcast through the port: plumtree over hyparview reaches
    every node ({plumtree, true} start prop)."""
    n = 16
    pc = _pc("hv16pt")
    assert pc.start("hyparview", n_nodes=n, shuffle_interval=5,
                    plumtree=True, data_plane=False) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, n)])
    pc.advance(20)
    assert pc.pt_broadcast(0, 0, 42) == _A("ok")
    pc.advance(20)
    vals = [pc.pt_read(i, 0) for i in range(n)]
    assert all(v == 42 for v in vals), vals


def port_otp_test():
    """otp_test (:1261) through the port: a gen_server call over the
    overlay doubles the request."""
    pc = _pc("otp4")
    assert pc.start("otp", n_nodes=4, inbox_cap=8) == _A("ok")
    assert pc.otp_call(1, 2, [21, 0], timeout=10) == _A("ok")
    pc.advance(4)
    replies, timed = pc.otp_results(1)
    assert timed == 0 and replies and replies[0][0] == 42, (replies, timed)


def port_rpc_test():
    """rpc_test (:813) through the port: call ships, applies remotely,
    fulfils the caller's promise."""
    pc = _pc("rpc4")
    assert pc.start("rpc", n_nodes=4, inbox_cap=8) == _A("ok")
    assert pc.rpc_call(1, 2, 0, 21) == _A("ok")   # fn 0 = double
    assert pc.rpc_call(1, 3, 1, 41) == _A("ok")   # fn 1 = increment
    pc.advance(4)
    res = pc.rpc_results(1)
    assert sorted(res) == [42, 42], res


def port_causal_sparse_test(acked=False):
    """causal_test (:402) through the port on the SPARSE-clock backend
    (no N<=128 cap); acked=True runs the with_causal_send_and_ack
    composition (CausalAckedSparse: reemit on loss, byte-identical
    deps)."""
    mgr = "causal_acked_sparse" if acked else "causal_sparse"
    pc = _pc(mgr + "4")
    assert pc.start(mgr, n_nodes=4, inbox_cap=8) == _A("ok")
    for k, d in ((1, 4), (2, 2), (3, 0)):
        assert pc.csend(0, 1, k, delay=d) == _A("ok")
        pc.advance(1)
    pc.advance(12)
    log, total = pc.clog(1)
    assert total == 3 and log == [1, 2, 3], (log, total)


def port_delay_test(field):
    """with_ingress/egress_delay through the port (start prop)."""
    pc = _pc(f"full4delay_{field}")
    assert pc.start("full", n_nodes=4, periodic_interval=2,
                    **{field + "_delay": 4}) == _A("ok")
    pc.forward(0, 2, 1, [9])
    pc.advance(4)
    assert pc.recv(2)[0] == []
    pc.advance(4)
    assert pc.recv(2)[0] == [(0, 1, [9, 0, 0, 0])]


def port_client_server_test():
    """client_server manager through the port: clients see servers
    only."""
    n = 6
    pc = _pc("cs6")
    assert pc.start("client_server", n_nodes=n, n_servers=2,
                    data_plane=False) == _A("ok")
    _port_join_all(pc, [(i, i % 2) for i in range(2, n)])
    pc.advance(20)
    for c in range(2, n):
        mem = set(pc.members(c))
        assert mem & {0, 1}, f"client {c} reached no server: {mem}"
        assert not mem & set(range(2, n)), \
            f"client {c} linked to clients: {mem}"


def port_leave_rejoin_test():
    """leave_test + rejoin_test through the port."""
    pc = _pc("full4")
    assert pc.start("full", n_nodes=4, periodic_interval=2) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, 4)])
    pc.advance(12)
    assert pc.leave(3) == _A("ok")
    pc.advance(12)
    assert 3 not in pc.members(0), pc.members(0)
    assert pc.join(3, 0) == _A("ok")
    pc.advance(16)
    assert pc.members(0) == [0, 1, 2, 3]


def port_crash_recover_test():
    """crash/recover through the port: a crashed node receives nothing;
    after recovery an acked send lands via retransmission."""
    pc = _pc("full4")
    assert pc.start("full", n_nodes=4, periodic_interval=2) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, 4)])
    pc.advance(12)
    assert pc.call((_A("crash"), [3])) == _A("ok")
    pc.forward(1, 3, 7, [55], ack=True)
    pc.advance(6)
    assert pc.recv(3)[0] == []
    assert pc.call((_A("recover"), [3])) == _A("ok")
    pc.advance(8)
    recs, _ = pc.recv(3)
    assert (1, 7, [55, 0, 0, 0]) in recs, recs


def port_partition_key_test():
    """with_partition_key through the port: keyed forwards ride a
    deterministic lane (dispatch_pid :190-195)."""
    pc = _pc("full4par")
    assert pc.start("full", n_nodes=4, periodic_interval=2,
                    parallelism=4) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, 4)])
    pc.advance(12)
    for i in range(4):
        pc.forward((i + 1) % 4, i, i, [1000 + i], partition_key=3)
    pc.advance(4)
    for i in range(4):
        recs, _ = pc.recv(i)
        assert ((i + 1) % 4, i, [1000 + i, 0, 0, 0]) in recs, (i, recs)


def port_checkpoint_restore_test(tmpdir="/tmp"):
    """checkpoint/restore through the port: state round-trips and the
    session keeps working after restore."""
    import tempfile
    pc = _pc("full4")
    assert pc.start("full", n_nodes=4, periodic_interval=2) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, 4)])
    pc.advance(12)
    before = pc.members(0)
    path = tempfile.mktemp(prefix="pt_ckpt_", dir=tmpdir)
    assert pc.call((_A("checkpoint"), path)) == _A("ok")
    pc.advance(4)
    assert pc.call((_A("restore"), path)) == _A("ok")
    assert pc.members(0) == before
    pc.forward(1, 2, 5, [77])
    pc.advance(3)
    recs, _ = pc.recv(2)
    assert (1, 5, [77, 0, 0, 0]) in recs, recs
    import shutil
    shutil.rmtree(path, ignore_errors=True)


def port_ack_test():
    pc = _pc("full4")
    assert pc.start("full", n_nodes=4, periodic_interval=2) == _A("ok")
    _port_join_all(pc, [(i, 0) for i in range(1, 4)])
    pc.advance(12)
    assert pc.forward(1, 3, 7, [5], ack=True) == _A("ok")
    pc.advance(6)
    recs, _ = pc.recv(3)
    assert (1, 7, [5, 0, 0, 0]) in recs


def port_sync_join_test():
    pc = _pc("full4")
    assert pc.start("full", n_nodes=4, periodic_interval=2) == _A("ok")
    assert pc.sync_join(1, 0) >= 1


# ------------------------------------------------------------------ matrix

SKIP = {
    "with_tls": "TLS is transport-level; the simulated router has no "
                "socket layer to wrap (SURVEY §7.4)",
    "with_disterl": "disterl is the reference's control channel; replaced "
                    "by the port bridge (SURVEY §7.4)",
    "with_binary_padding": "BEAM shared-heap binary trick; no analog in "
                           "array payloads",
    "pid_test": "pid rewriting not ported (integer node ids only, "
                "SURVEY §7.4)",
    "with_parallelism_bypass_pid_encoding":
        "pid encoding not ported; plain parallelism perf covered",
    "with_partisan_bypass_pid_encoding":
        "pid encoding not ported; performance_test covered under default",
}


def trace_lint_clean_test():
    """ISSUE 11: Level-1 trace-lint over the whole package — zero
    unsuppressed findings, every pragma reasoned and live (the pure-AST
    pass; the no-JAX-import property is scripts/trace_lint.py's to
    assert, this process already has the real package loaded)."""
    import partisan_tpu
    from partisan_tpu.verify.lint import format_report, lint_tree
    pkg = os.path.dirname(os.path.abspath(partisan_tpu.__file__))
    findings = lint_tree(pkg, root=os.path.dirname(pkg))
    assert not findings, "\n" + format_report(findings)


def fingerprint_gate_test():
    """ISSUE 11: the lower-only compile-surface gate — re-trace and
    re-lower all flagship entrypoints and diff jaxpr-eqn / StableHLO
    collective counts against the committed LINT_fingerprints.json
    (fails on any collective change or >10% eqn growth; no XLA
    compile, so this row costs seconds, not the compile wall)."""
    from partisan_tpu.verify.lint import fingerprint as fp
    golden = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "LINT_fingerprints.json")
    errors = fp.check(golden)
    assert not errors, "\n".join(errors)


def stream_parity_test():
    """ISSUE 14: mid-scan streaming vs windowed flush — the ordered
    io_callback drain must produce BIT-EQUAL rows (same float32 pack
    source) and stream=None must lower byte-identically (the
    flight=None discipline; the flagship cache entries depend on it)."""
    import partisan_tpu as _pt
    from partisan_tpu import peer_service, telemetry
    from partisan_tpu.models.hyparview import HyParView

    class Rows:
        def __init__(self):
            self.rows = []

        def write_row(self, r):
            self.rows.append(dict(r))

        def close(self):
            pass

    n = 64
    cfg = _pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5, seed=3)
    proto = HyParView(cfg)
    world = peer_service.cluster(
        _pt.init_world(cfg, proto), proto,
        [(i, (i - 1) // 2) for i in range(1, n)])
    reg = telemetry.default_registry()
    sink = Rows()
    telemetry.run_with_telemetry(cfg, proto, 32, window=16, registry=reg,
                                 sinks=[sink], world=world)
    spec = telemetry.StreamSpec(keep_rows=True)
    telemetry.run_with_telemetry(cfg, proto, 32, window=16, registry=reg,
                                 sinks=[Rows()], world=world, stream=spec)
    windowed = [r for r in sink.rows
                if "round" in r and "rounds_per_sec" not in r]
    assert spec.rows == windowed, "streamed rows != windowed flush rows"
    ring = telemetry.make_ring(reg, 16)
    t_off = telemetry.make_window_runner(
        cfg, proto, reg, 16, stream=None).lower(world, ring).as_text()
    t_base = telemetry.make_window_runner(
        cfg, proto, reg, 16).lower(world, ring).as_text()
    assert t_off == t_base, "stream=None is not byte-identical"


def compile_ledger_gate_test():
    """ISSUE 14: the LIVE recompile-regression gate — replay every
    flagship entrypoint against COMPILE_goldens.json with the
    monitoring ledger armed; any module drift or persistent-cache miss
    where the golden pins a hit fails this row by name (the CLI
    equivalent is scripts/observatory.py --check)."""
    from partisan_tpu.telemetry import observatory as obs
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    golden = os.path.join(repo, "COMPILE_goldens.json")
    assert os.path.exists(golden), \
        "missing COMPILE_goldens.json — run scripts/observatory.py --bless"
    prev = obs.configure_cache(os.path.join(repo, ".jax_cache"))
    ledger = obs.CompileLedger().install()
    try:
        errors = obs.check_goldens(golden, ledger=ledger, compile=True)
        assert not errors, "\n".join(errors)
    finally:
        ledger.close()
        obs.restore_cache(prev)


def aot_roundtrip_test():
    """ISSUE 17: the AOT export plane round trip — serialize ->
    deserialize -> execute the engine step at n=8 and compare every
    output leaf (state AND metrics) bitwise against the freshly-traced
    twin.  Uses the same program name as tests/test_aot.py so the
    persistent cache entry is shared; the flagship-shape equivalent is
    the cold_start_gate row."""
    import tempfile
    from partisan_tpu import aot

    def build():
        from partisan_tpu.models.hyparview import HyParView
        cfg = pt.Config(n_nodes=8, inbox_cap=8, shuffle_interval=5,
                        seed=3)
        proto = HyParView(cfg)
        world = pt.init_world(cfg, proto)
        return pt.make_step(cfg, proto, donate=False), (world,)

    name = "aot_test_engine_step_n8"
    with tempfile.TemporaryDirectory() as art:
        fn, args = build()
        aot.export_entry(name, fn, args, art_dir=art)
        rec = aot.verify_entry(name, art_dir=art, registry={name: build})
        assert rec["bit_identical"], rec


def cold_start_gate():
    """ISSUE 17 gate: ``scripts/aot_pack.py --verify`` over the
    committed bundle manifest — every flagship artifact must
    deserialize and execute bit-identical to its freshly-traced twin.
    Fails NAMED when the bundle is absent (build it with
    ``python scripts/aot_pack.py --build``)."""
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    manifest = os.path.join(repo, "aot_artifacts", "MANIFEST.json")
    assert os.path.exists(manifest), (
        "no aot_artifacts/MANIFEST.json — the bundle gate needs the "
        "built bundle (python scripts/aot_pack.py --build)")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "aot_pack.py"),
         "--verify"], capture_output=True, text=True, timeout=3000)
    assert proc.returncode == 0, \
        (proc.stdout or "") + (proc.stderr or "")


def perf_gate_check_test():
    """ISSUE 18 gate: ``scripts/perf_gate.py --check --only perf`` —
    replay the pinned flagship micro-round subset (AOT-loaded, no
    compile wall) against the committed PERF_goldens.json; a
    calibration-normalized rounds/sec drop past the fail band fails
    this row by name.  The budget half runs as its own row below so a
    throughput regression and a runtime overrun stay separately
    attributable."""
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    golden = os.path.join(repo, "PERF_goldens.json")
    assert os.path.exists(golden), \
        "missing PERF_goldens.json — run scripts/perf_gate.py --bless"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "perf_gate.py"),
         "--check", "--only", "perf"],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, \
        (proc.stdout or "") + (proc.stderr or "")


def runtime_budget_gate():
    """ISSUE 18 gate: the tier-1 runtime budget — every per-test
    duration in BENCH_suite_durations.jsonl within its committed
    (calibration-normalized) budget, and the projected full-suite
    total inside the 870 s ceiling's noise band (raw same-box
    seconds; a timeout-truncated artifact totals ≈ the wall, so the
    fail line sits ceiling_slack_pct above it).  Fails NAMED per
    overrunning test, so the PR that slows the suite hears about it,
    not the PR three later that trips CI truncation."""
    import json as _json
    from partisan_tpu.telemetry import benchplane as bp
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    golden = os.path.join(repo, "PERF_goldens.json")
    assert os.path.exists(golden), \
        "missing PERF_goldens.json — run scripts/perf_gate.py --bless"
    with open(golden) as f:
        budget = _json.load(f).get("suite_budget")
    assert budget, ("PERF_goldens.json has no suite_budget — run a "
                    "clean tier-1, then scripts/perf_gate.py --bless "
                    "--only budget")
    dur = os.path.join(repo, "BENCH_suite_durations.jsonl")
    assert os.path.exists(dur), \
        "no BENCH_suite_durations.jsonl — run tier-1 first"
    errors, _warnings, info = bp.check_budget(budget, dur)
    assert not errors, "\n".join(errors)
    assert info["projected_s"] <= info["ceiling_fail_s"], info


def span_parity_test():
    """ISSUE 16 tentpole contract: the message lifecycle tracer records
    the SAME span-event multiset (EXCHANGED excluded — it only exists
    where an exchange exists) through the unsharded engine and the
    8-device shard_map dataplane, with zero overflow on both sides, and
    ``trace=None`` lowers the byte-identical program on both paths."""
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.parallel import make_mesh
    from partisan_tpu.parallel.dataplane import (
        make_sharded_step, place_sharded_world, sharded_out_cap)
    from partisan_tpu.telemetry import tracer as tr
    n, rounds = 16, 12
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5)
    proto = _manager("hyparview", cfg)
    mesh = make_mesh(n_devices=8)
    out_cap = sharded_out_cap(cfg, proto, 8)
    spec = tr.TraceSpec(window=rounds, cap=4 * out_cap)
    pairs = [(i, i - 1) for i in range(1, n)]
    w = ps.cluster(pt.init_world(cfg, proto, out_cap=out_cap), proto,
                   pairs)

    def drain(step, world, ring):
        for _ in range(rounds):
            world, ring, _m = step(world, ring)
        rows, overflow, _ = tr.trace_flush(ring)
        return tr.trace_events(rows), overflow

    ustep = pt.make_step(cfg, proto, donate=False, trace=spec)
    uevents, uov = drain(ustep, w, tr.make_trace_ring(spec))
    sstep = make_sharded_step(cfg, proto, mesh, donate=False,
                              trace=spec)
    sevents, sov = drain(
        sstep, place_sharded_world(w, cfg, mesh),
        tr.place_trace_ring(tr.make_trace_ring(spec, 8), mesh))
    assert uov == 0 and sov == 0
    key = lambda e: (e.rnd, e.ev, e.src, e.dst, e.typ, e.born, e.seq)
    um = sorted(key(e) for e in uevents if e.ev != tr.EV_EXCHANGED)
    sm = sorted(key(e) for e in sevents if e.ev != tr.EV_EXCHANGED)
    assert um == sm and um
    assert any(e.ev == tr.EV_EXCHANGED for e in sevents)
    # off-path: trace=None is byte-identical on both dataplanes
    base = pt.make_step(cfg, proto, donate=False)
    off = pt.make_step(cfg, proto, donate=False, trace=None)
    assert base.lower(w).as_text() == off.lower(w).as_text()


def alert_smoke():
    """ISSUE 16: the in-scan alert plane — a standing partition drives
    the partition-suspicion detector over its ``for:`` window, the
    firing transition reaches the host event bus through the runner,
    and the alert gauge round-trips through PrometheusSink text
    exposition."""
    from partisan_tpu import telemetry
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.telemetry import alerts as al
    from partisan_tpu.verify import health as vh
    n = 16
    cfg = pt.Config(n_nodes=n, inbox_cap=16)
    proto = _manager("hyparview", cfg)
    world = ps.cluster(pt.init_world(cfg, proto), proto,
                       [(i, (i + 1) % n) for i in range(n)])
    world = world.replace(partition=jnp.where(
        jnp.arange(n) < n // 2, 1, 2).astype(jnp.int32))
    reg = vh.health_registry()
    firer = al.AlertFirer()
    sink = telemetry.PrometheusSink(al.alert_registry(reg))
    telemetry.run_with_telemetry(
        cfg, proto, 16, window=8, registry=reg, world=world,
        sinks=[sink], alerts=al.AlertSpec(partition_rounds=3),
        alert_firer=firer)
    assert "partition_suspected" in firer.firing()
    parsed = telemetry.parse_exposition(sink.expose())
    assert parsed["partisan_alert_partition"]["samples"][""] == 1.0
    assert 'alertname="partition_suspected"' in al.alerts_exposition(firer)


def byzantine_parity_test():
    """ISSUE 19 tentpole contract: one compiled ChaosSchedule carrying
    the full Byzantine alphabet (equivocate + corrupt + replay + forge
    on top of partition/heal and a duplicate) AND a two-region WAN
    latency plane over HyParView through the shard_map dataplane
    bit-matches the unsharded run — states, fault planes, per-round
    metrics INCLUDING the four Byzantine counters — with the
    2-collective budget unchanged both planes on."""
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.parallel import make_mesh
    from partisan_tpu.parallel.dataplane import (
        make_sharded_step, place_sharded_world, sharded_out_cap)
    from partisan_tpu.parallel.mesh import assert_collective_budget
    from partisan_tpu.verify.chaos import ChaosSchedule
    from partisan_tpu.verify.latency import LatencyPlane
    n, rounds = 64, 30
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5)
    proto = HyParView(cfg)
    t_keep = proto.typ("keepalive")
    t_neigh = proto.typ("neighbor")
    sched = (ChaosSchedule()
             .partition(10, (0, 31), 1).partition(10, (32, 63), 2)
             .equivocate(14, typ=t_keep, salt=3)
             .corrupt(13, salt=5)
             .replay(14, typ=t_keep, after=3)
             .forge(15, src=3, dst=11, typ=t_neigh)
             .duplicate(16, src=4)
             .heal(20))
    plane = LatencyPlane(regions=(0,) * (n // 2) + (1,) * (n // 2),
                         base_rtt=((0, 2), (2, 0)),
                         jitter_milli=50, seed=19)
    mesh = make_mesh(n_devices=8)
    pairs = [(i, i - 1) for i in range(1, n)]
    w = ps.cluster(pt.init_world(cfg, proto), proto, pairs, stagger=16)
    step = pt.make_step(cfg, proto, donate=False, chaos=sched,
                        latency=plane)
    w2 = ps.cluster(
        pt.init_world(cfg, proto,
                      out_cap=sharded_out_cap(cfg, proto, 8)),
        proto, pairs, stagger=16)
    w2 = place_sharded_world(w2, cfg, mesh)
    sstep = make_sharded_step(cfg, proto, mesh, donate=False,
                              chaos=sched, latency=plane)
    st = assert_collective_budget(
        sstep.lower(w2).compile(), max_collectives=2,
        max_bytes=32 * 1024 * 1024, forbid=("all-gather",))
    assert st["counts"]["all-to-all"] == 1
    byz = {k: 0 for k in ("chaos_equivocated", "chaos_forged",
                          "chaos_replayed", "chaos_corrupted")}
    for _ in range(rounds):
        w, mp = step(w)
        w2, msh = sstep(w2)
        assert all(int(msh[k]) == int(v) for k, v in mp.items()), \
            (mp, msh)
        for k in byz:
            byz[k] += int(mp[k])
    assert all(v > 0 for v in byz.values()), byz
    for lp, lsh in zip(jax.tree_util.tree_leaves((w.state, w.alive,
                                                  w.partition)),
                       jax.tree_util.tree_leaves((w2.state, w2.alive,
                                                  w2.partition))):
        assert (np.asarray(lp) == np.asarray(lsh)).all()


def wan_soak_smoke():
    """ISSUE 19 campaign smoke: the real chaos_soak CLI over the
    byzantine_combo mix at smoke scale must converge, report all four
    Byzantine counters nonzero, and write its JSONL row — with the
    PR-18 ledger env-pinned so smoke rows never dirty the committed
    trajectory."""
    import importlib.util
    import json
    import tempfile
    spec = importlib.util.spec_from_file_location(
        "chaos_soak", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "chaos_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "bench.jsonl")
        prev = os.environ.get("PARTISAN_BENCH_LEDGER")
        os.environ["PARTISAN_BENCH_LEDGER"] = os.path.join(
            td, "ledger.jsonl")
        try:
            rc = soak.main(["--smoke", "--mixes", "byzantine_combo",
                            "--out", out, "--postmortem-dir", td])
        finally:
            if prev is None:
                os.environ.pop("PARTISAN_BENCH_LEDGER", None)
            else:
                os.environ["PARTISAN_BENCH_LEDGER"] = prev
        assert rc == 0
        with open(out) as f:
            rows = [json.loads(line) for line in f]
        with open(os.path.join(td, "ledger.jsonl")) as f:
            ledger = [json.loads(line) for line in f]
    assert rows and rows[0]["mix"] == "byzantine_combo"
    assert rows[0]["converged"], rows[0]
    for k in ("chaos_equivocated", "chaos_forged", "chaos_replayed",
              "chaos_corrupted"):
        assert rows[0][k] > 0, (k, rows[0])
    assert any(r.get("suite") == "chaos_soak"
               and r.get("arm") == "byzantine_combo" for r in ledger)


def build_matrix():
    """(group, test, manager, path, fn_or_skipreason) rows mirroring
    all/0 + groups/0 of test/partisan_SUITE.erl:121-308.

    Port-bridge rows run FIRST: each spawns a fresh subprocess, and
    running them before the ~40 in-process engine compiles bloat this
    driver's memory keeps subprocess startup reliable on the 1-vCPU
    box."""
    M = []
    add = lambda *row: M.append(row)

    # the CT contracts over the port bridge (the Erlang-facing path;
    # >= 20 rows, VERDICT r2 #3 — sessions pooled per config profile,
    # join storms batched into single frames)
    add("default/simple", "basic_test", "full", "port",
        lambda: port_basic_test(profile="full4"))
    add("default/simple", "leave_test+rejoin_test", "full", "port",
        port_leave_rejoin_test)
    add("default/simple", "client_server_manager_test", "client_server",
        "port", port_client_server_test)
    add("default/hyparview", "connectivity_test", "hyparview", "port",
        lambda: port_connectivity_test("hyparview"))
    add("default/hyparview", "hyparview_manager_partition_test",
        "hyparview", "port", port_hyparview_partition_test)
    add("default/hyparview", "hyparview_manager_high_active_test",
        "hyparview", "port", port_hyparview_high_active_test)
    add("with_full_membership_strategy", "connectivity_test", "full",
        "port", lambda: port_connectivity_test("full"))
    add("with_scamp_v1_membership_strategy", "connectivity_test",
        "scamp_v1", "port", lambda: port_connectivity_test("scamp_v1"))
    add("with_scamp_v2_membership_strategy", "connectivity_test",
        "scamp_v2", "port", lambda: port_connectivity_test("scamp_v2"))
    add("with_ack", "ack_test", "full", "port", port_ack_test)
    add("with_causal_labels", "causal_test", "full", "port",
        port_causal_test)
    add("with_channels", "basic_test", "full", "port",
        lambda: port_basic_test(
            profile="full4ch", channel=1,
            channels=["undefined", "rpc", "membership"]))
    add("with_monotonic_channels", "basic_test", "full", "port",
        port_monotonic_test)
    add("with_forward_interposition", "forward_interposition_test",
        "full", "port", lambda: port_interposition_test("forward"))
    add("with_receive_interposition", "receive_interposition_test",
        "full", "port", lambda: port_interposition_test("receive"))
    add("with_forward_delay_interposition",
        "forward_delay_interposition_test", "full", "port",
        lambda: port_interposition_test("forward_delay"))
    add("with_broadcast", "broadcast_test", "hyparview", "port",
        port_broadcast_test)
    add("with_ingress_delay", "basic_test", "full", "port",
        lambda: port_delay_test("ingress"))
    add("with_egress_delay", "basic_test", "full", "port",
        lambda: port_delay_test("egress"))
    add("with_partition_key", "basic_test", "full", "port",
        port_partition_key_test)
    add("with_sync_join", "basic_test", "full", "port", port_sync_join_test)
    add("with_parallelism", "basic_test", "full", "port",
        lambda: port_basic_test(profile="full4par", parallelism=4))
    add("default/simple", "crash_recover_test", "full", "port",
        port_crash_recover_test)
    add("default/simple", "checkpoint_restore_test", "full", "port",
        port_checkpoint_restore_test)
    # VERDICT r3 #8: OTP / RPC / sparse-causal groups through the port
    add("default/simple", "otp_test", "otp", "port", port_otp_test)
    add("default/simple", "rpc_test", "rpc", "port", port_rpc_test)
    add("with_causal_send", "causal_test", "causal_sparse", "port",
        lambda: port_causal_sparse_test(acked=False))
    add("with_causal_send_and_ack", "causal_test", "causal_acked_sparse",
        "port", lambda: port_causal_sparse_test(acked=True))

    # default group: simple + hyparview
    add("default/simple", "basic_test", "full", "engine", basic_test)
    add("default/simple", "leave_test", "full", "engine", leave_test)
    add("default/simple", "self_leave_test", "full", "engine",
        lambda: leave_test(self_leave=True))
    add("default/simple", "on_down_test", "full", "engine", on_down_test)
    add("default/simple", "rpc_test", "full", "engine", rpc_test)
    add("default/simple", "client_server_manager_test", "client_server",
        "engine", client_server_manager_test)
    add("default/simple", "pid_test", "full", "engine", SKIP["pid_test"])
    add("default/simple", "rejoin_test", "full", "engine", rejoin_test)
    add("default/simple", "transform_test", "full", "engine", transform_test)
    add("default/simple", "otp_test", "full", "engine", otp_test)
    add("default/hyparview", "hyparview_manager_partition_test",
        "hyparview", "engine", hyparview_partition_test)
    add("default/hyparview", "hyparview_manager_high_active_test",
        "hyparview", "engine", hyparview_high_active_test)
    add("default/hyparview", "hyparview_manager_high_client_test",
        "client_server", "engine", hyparview_high_client_test)

    # membership strategies
    for mgr in ("full", "scamp_v1", "scamp_v2"):
        g = f"with_{mgr}_membership_strategy"
        add(g, "connectivity_test", mgr, "engine",
            lambda mgr=mgr: connectivity_test(mgr))
        add(g, "gossip_test", mgr, "engine",
            lambda mgr=mgr: gossip_test(mgr))

    # features
    add("with_ack", "basic_test", "full", "engine", basic_test)
    add("with_ack", "ack_test", "full", "engine", ack_test)
    add("with_causal_labels", "causal_test", "full", "engine", causal_test)
    add("with_causal_send", "basic_test", "full", "engine", causal_test)
    add("with_causal_send_and_ack", "basic_test", "full", "engine",
        causal_test)
    add("with_forward_interposition", "forward_interposition_test", "full",
        "engine", lambda: interposition_test("forward"))
    add("with_forward_delay_interposition",
        "forward_delay_interposition_test", "full", "engine",
        lambda: interposition_test("forward_delay"))
    add("with_receive_interposition", "receive_interposition_test", "full",
        "engine", lambda: interposition_test("receive"))
    add("with_tls", "basic_test", "full", "engine", SKIP["with_tls"])
    add("with_parallelism", "basic_test", "full", "engine",
        parallelism_test)
    add("with_parallelism_bypass_pid_encoding", "performance_test", "full",
        "engine", SKIP["with_parallelism_bypass_pid_encoding"])
    add("with_partisan_bypass_pid_encoding", "performance_test", "full",
        "engine", SKIP["with_partisan_bypass_pid_encoding"])
    add("with_disterl", "performance_test", "full", "engine",
        SKIP["with_disterl"])
    add("default", "performance_test", "full", "engine", performance_test)
    add("with_channels", "basic_test", "full", "engine",
        lambda: channels_test(("undefined", "rpc", "membership")))
    add("with_channels", "rpc_test", "full", "engine",
        lambda: channels_test(("undefined", "rpc"), rpc_on_channel=True))
    add("with_no_channels", "basic_test", "full", "engine",
        lambda: channels_test(("undefined",)))
    add("with_monotonic_channels", "basic_test", "full", "engine",
        lambda: channels_test(("undefined", "mono"), monotonic=("mono",)))
    add("with_sync_join", "basic_test", "full", "engine", sync_join_test)
    add("with_binary_padding", "basic_test", "full", "engine",
        SKIP["with_binary_padding"])
    add("with_partition_key", "basic_test", "full", "engine",
        partition_key_test)
    add("with_ingress_delay", "basic_test", "full", "engine",
        lambda: delay_test("ingress"))
    add("with_egress_delay", "basic_test", "full", "engine",
        lambda: delay_test("egress"))
    add("with_broadcast", "hyparview_manager_high_active_test",
        "hyparview", "engine", broadcast_test)

    # ISSUE 2: the explicit shard_map dataplane + dense-phase cadences
    # as standing matrix rows (no reference analog — these are the
    # TPU-native distribution contracts the round-synchronous rebuild
    # adds on top of the CT matrix)
    add("multichip/dataplane", "sharded_dataplane_parity_test",
        "hyparview", "engine", sharded_dataplane_parity_test)
    add("multichip/dataplane", "collective_budget_test", "hyparview",
        "engine", collective_budget_test)
    add("dense_cadence", "scamp_stagger_equivalence_test", "scamp_v2",
        "engine", scamp_stagger_equivalence_test)
    add("dense_cadence", "plumtree_lazy_equivalence_test", "hyparview",
        "engine", plumtree_lazy_equivalence_test)

    # ISSUE 3: the in-scan message flight recorder — trace parity on
    # both execution paths and dataplane telemetry coverage (the
    # partisan_trace_orchestrator contract at scan speed)
    add("observability/flight", "flight_recorder_parity_test",
        "hyparview", "engine", flight_recorder_parity_test)
    add("observability/flight", "dataplane_flight_telemetry_test",
        "hyparview", "engine", dataplane_flight_telemetry_test)

    # ISSUE 4: the compiled chaos plane — sharded/unsharded fault
    # parity under one schedule, and the campaign runner's smoke cell
    # (full seed x mix campaigns live in scripts/chaos_soak.py)
    add("robustness/chaos", "chaos_parity_test", "hyparview", "engine",
        chaos_parity_test)
    add("robustness/chaos", "chaos_soak_smoke", "hyparview", "engine",
        chaos_soak_smoke)

    # ISSUE 19: the Byzantine fault alphabet + geo/WAN latency plane —
    # sharded/unsharded bit-parity with both planes on, and the real
    # byzantine_combo campaign cell through the chaos_soak CLI (full
    # wan_{1,20,100} sweeps live in scripts/chaos_soak.py)
    add("robustness/byzantine", "byzantine_parity_test", "hyparview",
        "engine", byzantine_parity_test)
    add("robustness/byzantine", "wan_soak_smoke", "hyparview", "engine",
        wan_soak_smoke)

    # ISSUE 8: the device-side workload plane — latency-histogram
    # parity on both execution paths and the capacity-bench harness
    # smoke (full offered-load sweeps live in scripts/load_suite.py)
    add("workload/load", "latency_parity_test", "full", "engine",
        latency_parity_test)
    add("workload/load", "load_suite_smoke", "hyparview", "engine",
        load_suite_smoke)

    # ISSUE 7: the batched fault-space explorer — B=1 vmapped/static
    # bit-identity and the find -> shrink -> replay campaign smoke
    # (full frontiers live in scripts/chaos_explore.py)
    add("robustness/explore", "explorer_parity_test", "hyparview",
        "engine", explorer_parity_test)
    add("robustness/explore", "explore_smoke", "hyparview", "engine",
        explore_smoke)

    # ISSUE 9: the explicit-SPMD dense dataplane — the per-model
    # collective-budget pin and one implicit-vs-explicit bench window
    # (full N sweeps live in scripts/dense_scale_suite.py)
    add("perf/dense", "dense_budget_test", "hyparview", "engine",
        dense_budget_test)
    add("perf/dense", "dense_scale_smoke", "hyparview", "engine",
        dense_scale_smoke)

    # ISSUE 10: the adaptive control plane — host-twin / sharded
    # bit-parity + the controllers-on budget pin, and one tiny
    # static-vs-adaptive bench cell (full arms live in
    # scripts/control_suite.py -> BENCH_control.jsonl)
    add("control/adaptive", "control_parity_test", "hyparview",
        "engine", control_parity_test)
    add("control/adaptive", "control_suite_smoke", "hyparview",
        "engine", control_suite_smoke)

    # ISSUE 11: trace-lint — the clean-tree AST gate and the lower-only
    # program-fingerprint diff against LINT_fingerprints.json (the CLI
    # equivalent is scripts/trace_lint.py --check)
    add("analysis/lint", "trace_lint_clean", "hyparview", "engine",
        trace_lint_clean_test)
    add("analysis/lint", "fingerprint_gate", "hyparview", "engine",
        fingerprint_gate_test)

    # ISSUE 14: the compile observatory — streamed-vs-windowed row
    # parity (+ the stream=None byte-identity the cache entries depend
    # on) and the live recompile-regression gate over the warm
    # .jax_cache (CLI: scripts/observatory.py --check)
    add("observability/observatory", "stream_parity_test", "hyparview",
        "engine", stream_parity_test)
    add("observability/observatory", "compile_ledger_gate", "hyparview",
        "engine", compile_ledger_gate_test)

    # ISSUE 16: the message lifecycle tracer — sharded/unsharded span
    # multiset parity (+ the trace=None byte-identity) and the in-scan
    # alert plane's Prometheus round-trip (span CLI:
    # scripts/trace_report.py)
    add("observability/tracer", "span_parity_test", "hyparview",
        "engine", span_parity_test)
    add("observability/tracer", "alert_smoke", "hyparview", "engine",
        alert_smoke)

    # ISSUE 17: the AOT export plane — small-shape round-trip
    # bit-identity and the committed-bundle gate (scripts/aot_pack.py
    # --verify over aot_artifacts/MANIFEST.json)
    add("perf/aot", "aot_roundtrip_test", "hyparview", "engine",
        aot_roundtrip_test)
    add("observability/perf", "perf_gate_check", "hyparview", "engine",
        perf_gate_check_test)
    add("observability/perf", "runtime_budget_gate", "hyparview",
        "engine", runtime_budget_gate)
    add("perf/aot", "cold_start_gate", "hyparview", "engine",
        cold_start_gate)

    return M


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="suite_matrix.csv")
    ap.add_argument("--only", default=None)
    ap.add_argument("--engine-only", action="store_true")
    ap.add_argument("--path", default=None, choices=("engine", "port"),
                    help="run only one path's rows (debug aid; rows are "
                         "not written, like --only)")
    args = ap.parse_args()

    rows = []
    failures = 0
    for group, test, mgr, path, fn in build_matrix():
        if args.only and args.only not in f"{group}/{test}":
            continue
        if args.engine_only and path != "engine":
            continue
        if args.path and path != args.path:
            continue
        if isinstance(fn, str):
            rows.append([group, test, mgr, path, "skipped", fn])
            print(f"SKIP {group}/{test} [{path}]: {fn}")
            continue
        t0 = time.time()
        try:
            fn()
            rows.append([group, test, mgr, path, "pass",
                         f"{time.time() - t0:.1f}s"])
            print(f"PASS {group}/{test} [{path}] ({time.time() - t0:.1f}s)")
        except Exception as e:  # noqa: BLE001
            failures += 1
            detail = f"{type(e).__name__}: {e}"[:160].replace("\n", " ")
            rows.append([group, test, mgr, path, "fail", detail])
            print(f"FAIL {group}/{test} [{path}]: {detail}")
            traceback.print_exc()
    _pool_close()
    if args.only or args.engine_only or args.path:
        # a filtered run is a debugging aid — never clobber the full
        # artifact with a partial row set
        print(f"\n{len(rows)} filtered rows (NOT written); "
              f"{failures} failures")
    else:
        with open(args.out, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["group", "test", "manager", "path", "result",
                        "detail"])
            w.writerows(rows)
        print(f"\n{len(rows)} rows -> {args.out}; {failures} failures")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
