"""Adaptive-control bench (ISSUE 10): closed loops vs static knobs.

Two verdicts, both measured past the PR-8 saturation knee
(BENCH_load.jsonl: knee at 6.0 req/node/round, static shed arm 4000
holds p99 within the 16-round SLO):

  * ``admission``  — offered load pinned PAST the knee (default 8.0
    req/node/round).  Static token-rate arms (the PR-8 shedding knob at
    3000/4000/5000 milli-tokens) vs the AIMD admission controller
    closing on the ``rpc_slo_violated`` per-round delta.  BAR: the
    adaptive arm's goodput (SLO-met completions) must reach at least
    the best static arm that holds p99 <= SLO — without knowing the
    knee in advance.
  * ``chaos retransmit`` — a compiled partition-then-heal outage
    (verify.chaos.ChaosSchedule) under the acked-delivery protocol.
    Fixed retransmit timer vs the adaptive-backoff controller (AIMD on
    the ``ack_acked`` delta: double the base interval while acks stall,
    decay when they resume).  Both arms run the SAME protocol
    (AdaptiveAcked), differing ONLY in controllers on/off.  BAR: equal
    delivery (every message eventually acked, zero dead-letters) with
    strictly fewer retransmissions in the adaptive arm.

The sharded arm re-asserts the collective budget with controllers ON:
exactly {all-to-all: 1, all-reduce: 1, all-gather: 0} — closing the
loops adds zero collectives (the plane feeds on the one stacked psum
the dataplane already emits).

Measurement plumbing (make_cfg / build / measure / find_knee) is
imported from scripts/load_suite.py — one pipeline, two benches.

Usage:
    python scripts/control_suite.py                    # full bench
        [--n 4096] [--offered 8000] [--static-arms 3000,4000,5000]
        [--rounds 32] [--warm 8] [--chaos-n 64]
        [--sharded-n 512] [--skip-sharded] [--out BENCH_control.jsonl]
    python scripts/control_suite.py --smoke            # tiny tier-1 cell
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_here = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "load_suite", os.path.join(_here, "load_suite.py"))
ls = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ls)  # also pins JAX to CPU + the warm .jax_cache

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import partisan_tpu as pt  # noqa: E402
from partisan_tpu import peer_service as ps  # noqa: E402
from partisan_tpu.control import (  # noqa: E402
    ControlSpec, Controller, attach_plane)
from partisan_tpu.models.hyparview import HyParView  # noqa: E402
from partisan_tpu.models.stack import Lifted, Stacked  # noqa: E402
from partisan_tpu.ops import msg as msgops  # noqa: E402
from partisan_tpu.qos.ack import AdaptiveAcked  # noqa: E402
from partisan_tpu.verify.chaos import ChaosSchedule  # noqa: E402
from partisan_tpu.workload import arrivals  # noqa: E402
from partisan_tpu.workload.driver import AdaptiveWorkloadRpc  # noqa: E402


def admission_spec(lo: int = 1000, hi: int = 8000,
                   init: int = 4000) -> ControlSpec:
    """The admission loop: SLO violations this round -> shrink the
    token rate x0.9; a clean round -> climb +200 milli-tokens."""
    return ControlSpec((
        Controller(name="admit", metric="rpc_slo_violated",
                   actuator="wl.shed_rate_milli", kind="aimd",
                   init=init, target_milli=0, sense=1, delta=True,
                   alpha_milli=400, add=200, mult_milli=900,
                   lo=lo, hi=hi),
    ))


def retransmit_spec(base: int, hi: int = 16) -> ControlSpec:
    """The adaptive-backoff loop: acks stalled (delta below ~0.5/round)
    -> double the base retransmit interval toward ``hi``; acks flowing
    -> decay back toward the configured base."""
    return ControlSpec((
        Controller(name="retx", metric="ack_acked",
                   actuator="ack.retransmit_base", kind="aimd",
                   init=base, target_milli=500, sense=-1, delta=True,
                   alpha_milli=1000, add=-1, mult_milli=2000,
                   lo=base, hi=hi),
    ))


def build_adm(cfg: pt.Config, rate0: int, shed0: int):
    """The admission-arm stack: AdaptiveWorkloadRpc so the token rate
    is a STATE column — one compiled program serves every static arm."""
    n = cfg.n_nodes
    drv = AdaptiveWorkloadRpc(
        cfg, promise_cap=ls.PROMISE_CAP,
        spec=arrivals.ArrivalSpec(kind=arrivals.POISSON,
                                  max_issue=ls.MAX_ISSUE),
        rate_milli=rate0, shed_rate_milli=shed0)
    proto = Stacked(HyParView(cfg), Lifted(drv))
    world = ps.cluster(pt.init_world(cfg, proto), proto,
                       [(i, (i - 1) // 2) for i in range(1, n)])
    return proto, drv, world


def set_shed_rate(world, value: int):
    up = world.state.upper
    up = up.replace(wl_shed_rate_milli=jnp.full_like(
        up.wl_shed_rate_milli, jnp.int32(value)))
    return world.replace(state=world.state.replace(upper=up))


def run_admission(n: int, offered: int, static_arms, rounds: int,
                  warm: int) -> list:
    """Offered load past the knee; static token-rate arms vs AIMD."""
    cfg = ls.make_cfg(n, shed_rate=4000)  # burst 16000 for every arm
    slo = cfg.slo_deadline_rounds
    rows = []

    proto, _drv, world0 = build_adm(cfg, offered, static_arms[0])
    step = pt.make_step(cfg, proto, donate=False)

    @jax.jit
    def run_scan(w):
        return jax.lax.scan(lambda wc, _: step(wc), w, None,
                            length=rounds)

    for shed in static_arms:
        w = set_shed_rate(world0, shed)
        t0 = time.perf_counter()
        w, ms = run_scan(w)
        jax.block_until_ready(w.rnd)
        row = {"bench": "control_suite", "arm": "static",
               "n_nodes": n, "offered_milli": offered,
               "shed_rate_milli": shed, "rounds": rounds, "warm": warm,
               "slo_deadline_rounds": slo,
               **ls.measure(ms, n, rounds, warm, slo),
               "wall_s": round(time.perf_counter() - t0, 2)}
        rows.append(row)
        print(f"[static {shed}] goodput={row['slo_ok']} "
              f"p99={row['p99']} shed={row['shed']}")

    spec = admission_spec(init=static_arms[len(static_arms) // 2])
    proto_a, _drv, world_a = build_adm(cfg, offered,
                                       static_arms[len(static_arms) // 2])
    world_a = attach_plane(world_a, spec)
    step_a = pt.make_step(cfg, proto_a, donate=False, control=spec)

    @jax.jit
    def run_scan_a(w):
        return jax.lax.scan(lambda wc, _: step_a(wc), w, None,
                            length=rounds)

    t0 = time.perf_counter()
    world_a, ms = run_scan_a(world_a)
    jax.block_until_ready(world_a.rnd)
    sp = np.asarray(ms["ctl_admit__setpoint"])
    row = {"bench": "control_suite", "arm": "adaptive",
           "n_nodes": n, "offered_milli": offered,
           "shed_rate_milli": None, "rounds": rounds, "warm": warm,
           "slo_deadline_rounds": slo,
           **ls.measure(ms, n, rounds, warm, slo),
           "setpoint_first": int(sp[0]), "setpoint_last": int(sp[-1]),
           "setpoint_mean": float(sp[warm:].mean()),
           "wall_s": round(time.perf_counter() - t0, 2)}
    rows.append(row)
    print(f"[adaptive] goodput={row['slo_ok']} p99={row['p99']} "
          f"setpoint {row['setpoint_first']} -> {row['setpoint_last']} "
          f"(mean {row['setpoint_mean']:.0f})")
    return rows


def build_chaos(cfg: pt.Config, spec):
    """Same AdaptiveAcked protocol for BOTH chaos arms — the fixed arm
    simply never moves ``rt_base`` (control=None)."""
    n = cfg.n_nodes
    proto = AdaptiveAcked(cfg, ring_cap=4)
    world = pt.init_world(cfg, proto)
    if spec is not None:
        world = attach_plane(world, spec)
    # one tracked message per node, dst a fixed stride away: traffic
    # that MUST cross the partition cut for half the nodes
    nodes = jnp.arange(n, dtype=jnp.int32)
    em = proto.emit(nodes, proto.typ("ctl_send"), cap=n,
                    peer=(nodes + n // 2) % n, payload=nodes, seq=nodes)
    msgs, _ = msgops.inject(world.msgs, em, src=nodes, born=world.rnd)
    return proto, world.replace(msgs=msgs)


def run_chaos(n: int, rounds: int, outage: tuple) -> list:
    """Partition-then-heal outage; fixed vs adaptive retransmit base."""
    cfg = pt.Config(
        n_nodes=n, seed=5,
        retransmit_interval=2, retransmit_backoff_factor=1,
        retransmit_max_attempts=max(rounds, 64))
    o_start, o_end = outage
    sched = (ChaosSchedule()
             .partition(o_start, (0, n // 2 - 1), 1)
             .partition(o_start, (n // 2, n - 1), 2)
             .heal(o_end))
    rows = []
    for arm, spec in (("fixed", None),
                      ("adaptive", retransmit_spec(
                          cfg.retransmit_interval))):
        proto, world = build_chaos(cfg, spec)
        step = pt.make_step(cfg, proto, donate=False, chaos=sched,
                            control=spec)

        @jax.jit
        def run_scan(w, _step=step):
            return jax.lax.scan(lambda wc, _: _step(wc), w, None,
                                length=rounds)

        t0 = time.perf_counter()
        world, ms = run_scan(world)
        jax.block_until_ready(world.rnd)
        st = world.state
        delivered_origins = int(np.sum(np.asarray(st.seen) >= 1))
        row = {"bench": "control_suite", "arm": f"chaos_{arm}",
               "n_nodes": n, "rounds": rounds,
               "outage": [o_start, o_end],
               "delivered_origins": delivered_origins,
               "undelivered_slots": int(np.sum(np.asarray(st.out_valid))),
               "dead_lettered": int(np.sum(np.asarray(st.dead_lettered))),
               "retransmissions": int(np.sum(np.asarray(st.retx))),
               "acked": int(np.sum(np.asarray(st.acked))),
               "wall_s": round(time.perf_counter() - t0, 2)}
        if spec is not None:
            sp = np.asarray(ms["ctl_retx__setpoint"])
            row["base_peak"] = int(sp.max())
            row["base_last"] = int(sp[-1])
        rows.append(row)
        print(f"[chaos {arm}] delivered={delivered_origins}/{n} "
              f"retx={row['retransmissions']} "
              f"dead={row['dead_lettered']}"
              + (f" base peak={row.get('base_peak')}"
                 if spec is not None else ""))
    return rows


def run_sharded(n: int, offered: int, rounds: int, warm: int) -> list:
    """Controllers-ON collective budget on the 8-device mesh."""
    from partisan_tpu.parallel import mesh as pmesh
    from partisan_tpu.parallel.dataplane import (make_sharded_step,
                                                 place_world)
    cfg = ls.make_cfg(n, shed_rate=4000)
    spec = admission_spec()
    proto, _drv, world = build_adm(cfg, offered, 4000)
    world = attach_plane(world, spec)
    mesh = pmesh.make_mesh()
    world = place_world(world, mesh)
    step = make_sharded_step(cfg, proto, mesh, donate=False, control=spec)
    comp = step.lower(world).compile()
    st = pmesh.assert_collective_budget(
        comp, max_collectives=2, max_bytes=32 * 1024 * 1024,
        forbid=("all-gather",))
    counts = {k: int(v) for k, v in st["counts"].items()}
    assert counts.get("all-to-all", 0) == 1 \
        and counts.get("all-reduce", 0) == 1 \
        and counts.get("all-gather", 0) == 0, counts
    print(f"[sharded] collective budget controllers-on: {counts}")

    @jax.jit
    def run_scan(w):
        return jax.lax.scan(lambda wc, _: step(wc), w, None,
                            length=rounds)

    t0 = time.perf_counter()
    world, ms = run_scan(world)
    jax.block_until_ready(world.rnd)
    row = {"bench": "control_suite", "arm": "sharded_adaptive",
           "n_nodes": n, "offered_milli": offered, "rounds": rounds,
           "warm": warm, "collectives": counts,
           "slo_deadline_rounds": cfg.slo_deadline_rounds,
           **ls.measure(ms, n, rounds, warm, cfg.slo_deadline_rounds),
           "setpoint_last": int(np.asarray(ms["ctl_admit__setpoint"])[-1]),
           "wall_s": round(time.perf_counter() - t0, 2)}
    return [row]


def verdicts(adm_rows, chaos_rows) -> dict:
    slo = adm_rows[0]["slo_deadline_rounds"]
    static = [r for r in adm_rows if r["arm"] == "static"]
    adaptive = [r for r in adm_rows if r["arm"] == "adaptive"][0]
    holding = [r for r in static
               if not math.isinf(r["p99"]) and r["p99"] <= slo]
    best_static = max((r["slo_ok"] for r in holding), default=0)
    adaptive_holds = (not math.isinf(adaptive["p99"])
                      and adaptive["p99"] <= slo)
    fixed = [r for r in chaos_rows if r["arm"] == "chaos_fixed"][0]
    adapt = [r for r in chaos_rows if r["arm"] == "chaos_adaptive"][0]
    equal_delivery = (
        fixed["delivered_origins"] == adapt["delivered_origins"]
        and fixed["undelivered_slots"] == 0
        and adapt["undelivered_slots"] == 0
        and fixed["dead_lettered"] == 0 and adapt["dead_lettered"] == 0)
    return {
        "bench": "control_suite_summary",
        "slo_deadline_rounds": slo,
        "best_static_goodput_holding_slo": best_static,
        "static_arms_holding_slo": [r["shed_rate_milli"] for r in holding],
        "adaptive_goodput": adaptive["slo_ok"],
        "adaptive_p99": adaptive["p99"],
        "admission_bar": bool(adaptive_holds
                              and adaptive["slo_ok"] >= best_static),
        "chaos_fixed_retx": fixed["retransmissions"],
        "chaos_adaptive_retx": adapt["retransmissions"],
        "chaos_equal_delivery": equal_delivery,
        "chaos_bar": bool(equal_delivery
                          and adapt["retransmissions"]
                          < fixed["retransmissions"]),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--offered", type=int, default=8000)
    ap.add_argument("--static-arms", default="3000,4000,5000")
    ap.add_argument("--rounds", type=int, default=32)
    ap.add_argument("--warm", type=int, default=8)
    ap.add_argument("--chaos-n", type=int, default=64)
    ap.add_argument("--chaos-rounds", type=int, default=72)
    # the cut lands at round 2 — while the tracked messages' acks are
    # still in flight — so the whole outage window is spent retrying
    ap.add_argument("--outage", default="2,22")
    ap.add_argument("--sharded-n", type=int, default=512)
    ap.add_argument("--skip-sharded", action="store_true")
    ap.add_argument("--out", default="BENCH_control.jsonl")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny cell (n=64) — the tier-1 / suite_matrix "
                         "smoke configuration; bars reported, not "
                         "enforced")
    args = ap.parse_args(argv)

    if args.smoke:
        args.n, args.rounds, args.warm = 64, 16, 4
        args.static_arms = "3000,5000"
        args.chaos_n, args.chaos_rounds, args.outage = 32, 48, "2,14"
        args.sharded_n = 64
        if args.out == "BENCH_control.jsonl":
            args.out = "/tmp/BENCH_control_smoke.jsonl"

    static_arms = [int(r) for r in args.static_arms.split(",") if r]
    outage = tuple(int(r) for r in args.outage.split(","))
    assert args.warm >= 1 and args.rounds > args.warm

    t0 = time.perf_counter()
    adm_rows = run_admission(args.n, args.offered, static_arms,
                             args.rounds, args.warm)
    chaos_rows = run_chaos(args.chaos_n, args.chaos_rounds, outage)
    all_rows = adm_rows + chaos_rows
    if not args.skip_sharded:
        all_rows += run_sharded(args.sharded_n, args.offered,
                                args.rounds, args.warm)

    summary = verdicts(adm_rows, chaos_rows)
    summary["n_nodes"] = args.n
    summary["total_wall_s"] = round(time.perf_counter() - t0, 1)
    all_rows.append(summary)
    print(f"summary: {summary}")

    # unified bench ledger (ISSUE 18): one BenchRow per measured arm;
    # smoke runs land in /tmp like the legacy artifact
    from partisan_tpu.telemetry import benchplane
    ledger_path = os.environ.get("PARTISAN_BENCH_LEDGER") or (
        "/tmp/BENCH_ledger_smoke.jsonl" if args.smoke else None)
    calib = benchplane.calibrate()
    bench_rows = []
    for r in all_rows:
        if r.get("bench") != "control_suite" or "wall_s" not in r:
            continue
        rps = (round(r["rounds"] / r["wall_s"], 4)
               if r.get("rounds") and r.get("wall_s") else None)
        bench_rows.append(benchplane.make_row(
            "control_suite", r["arm"],
            config={k: r.get(k) for k in ("offered_milli",
                                          "shed_rate_milli", "outage")},
            n_nodes=r.get("n_nodes"), rounds=r.get("rounds"),
            rounds_per_sec=rps, wall_s=r.get("wall_s"),
            calibration=calib,
            metrics={k: r[k] for k in ("slo_ok", "p99",
                                       "delivered_origins",
                                       "retransmissions",
                                       "setpoint_last") if k in r}))
    benchplane.append_rows_nonfatal(bench_rows, ledger_path)

    with open(args.out, "w") as f:
        for row in all_rows:
            f.write(json.dumps(row) + "\n")
    print(f"{len(all_rows)} rows -> {args.out}")

    if not args.smoke and not (summary["admission_bar"]
                               and summary["chaos_bar"]):
        print("BAR FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
