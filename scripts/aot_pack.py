"""Build / verify the AOT artifact bundle (ISSUE 17 tentpole CLI).

``--build`` exports every flagship entrypoint (``verify/lint/
fingerprint.FLAGSHIP``) into ``aot_artifacts/``: the serialized
``jax.export`` blob, the pickled treedefs, and the persistent-cache
entry of the deserialized program keyed against the canonical
``.jax_cache`` path (see ``partisan_tpu/aot.py`` for why the path is
part of the key).  Each export pays the program's one real compile —
budget ~5-30 min for the full bundle on this box (the explorer checker
dominates); ``--entry`` narrows the pass.

``--verify`` is the bundle gate (suite_matrix ``perf/aot/
cold_start_gate``): for every manifest entry it retraces the flagship
twin, checks the module hash against the manifest (NAMED staleness on
drift), executes the deserialized program AND the twin, and fails
unless every output leaf is bit-identical.  Exit 1 on any failure.

Both modes attribute through the compile ledger
(``COMPILE_ledger.jsonl``): ``aot_export`` / ``aot_load`` /
``aot_stale`` rows, so ``scripts/observatory.py --report`` shows the
saved wall-clock as a tracked number.

Usage:
  python scripts/aot_pack.py --build  [--entry NAME ...]
  python scripts/aot_pack.py --verify [--entry NAME ...]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LEDGER = os.path.join(REPO, "COMPILE_ledger.jsonl")


def _jax_env() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--build", action="store_true",
                      help="export flagship programs into the bundle")
    mode.add_argument("--verify", action="store_true",
                      help="prove every artifact executes bit-identical "
                           "to its freshly-traced twin")
    ap.add_argument("--entry", action="append", default=None,
                    metavar="NAME", help="restrict to these entrypoints")
    ap.add_argument("--art-dir", default=None,
                    help="bundle dir (default <repo>/aot_artifacts)")
    ap.add_argument("--ledger", default=LEDGER)
    args = ap.parse_args(argv)

    _jax_env()
    from partisan_tpu import aot
    from partisan_tpu.telemetry import observatory as obs
    from partisan_tpu.verify.lint.fingerprint import FLAGSHIP

    names = args.entry
    if names:
        unknown = set(names) - set(FLAGSHIP)
        if unknown:
            print(f"aot_pack: unknown entrypoints {sorted(unknown)}; "
                  f"known: {sorted(FLAGSHIP)}", file=sys.stderr)
            return 2

    obs.configure_cache(aot.canonical_cache_dir(), record_all=True)
    ledger = obs.CompileLedger(path=args.ledger, mode="a").install()
    t0 = time.time()

    if args.build:
        built = {}
        for name in sorted(FLAGSHIP):
            if names and name not in names:
                continue
            t1 = time.time()
            print(f"  build {name} ...", flush=True)
            fn, a = FLAGSHIP[name]()
            with ledger.attribute(name):
                entry = aot.export_entry(name, fn, a,
                                         art_dir=args.art_dir,
                                         ledger=ledger)
            built[name] = entry
            print(f"  build {name}: {time.time() - t1:.1f}s "
                  f"module={entry['module_hash']} "
                  f"files={sorted(entry['files'].values())}", flush=True)
        print(f"aot_pack --build: {len(built)} artifacts -> "
              f"{args.art_dir or aot.artifact_dir()} "
              f"({time.time() - t0:.1f}s)")
        ledger.close()
        return 0

    # --verify
    manifest = aot.read_manifest(args.art_dir)
    if manifest is None:
        print(f"aot_pack --verify: no bundle manifest at "
              f"{args.art_dir or aot.artifact_dir()}", file=sys.stderr)
        return 1
    failures = []
    for name in sorted(manifest.get("entries", {})):
        if names and name not in names:
            continue
        t1 = time.time()
        if name not in FLAGSHIP:
            # bench-side exports (e.g. the dense_scale `aot` arm) have
            # no registry twin to retrace, so bit-identity can't be
            # re-proven here — but the artifact still has to pass the
            # full load gauntlet (env keys, file sha256s, deserialize)
            try:
                with ledger.attribute(name):
                    aot.load(name, art_dir=args.art_dir, ledger=ledger)
                print(f"  LOAD {name}: integrity ok — no flagship twin, "
                      f"bit-identity proven at export time "
                      f"({time.time() - t1:.1f}s)", flush=True)
            except aot.AotStale as e:
                failures.append(name)
                print(f"  FAIL {name}: {e}", flush=True)
            continue
        try:
            with ledger.attribute(name):
                res = aot.verify_entry(name, art_dir=args.art_dir,
                                       ledger=ledger)
            print(f"  PASS {name}: bit-identical "
                  f"({res['leaves']} leaves; load+call "
                  f"{res['load_call_s']}s vs twin exec "
                  f"{res['twin_exec_s']}s; {time.time() - t1:.1f}s total)",
                  flush=True)
        except (aot.AotStale, AssertionError) as e:
            failures.append(name)
            print(f"  FAIL {name}: {e}", flush=True)
    verdict = "PASS" if not failures else f"FAIL ({sorted(failures)})"
    print(f"aot_pack --verify: {verdict} ({time.time() - t0:.1f}s)")
    ledger.close()
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
