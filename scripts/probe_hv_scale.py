"""Probe dense HyParView beyond the 2^20 headline shape (2^21, 2^22):
staggered cadence in bounded launches (launch_cap_for), churn 1%/round,
then a churn-free heal and the hop-chunked connectivity readback.

The dense SCAMP/plumtree planes are gated at 2^20/2^21 (largest
validated shapes); the bare HyParView plane has no refuse gate, but
every shape step so far has found a limit eventually — this probe is
how the next row gets validated before any gate moves.

Probed ladder (v5e, jax 0.9.0 axon, 2026-08-01):
  2^21  clean at cap 50 (12.7 r/s staggered; official row)
  2^22  clean at cap 25 + 2-hop BFS launches (3.3 r/s; official row)
  2^23  COMPILE FAILURE — the remote TpuAotCompiler subprocess itself
        exits 1 on the staggered program (HTTP 500 from
        remote_compile; the compiler half of the ROADMAP-1d fault
        family, like round 4's scatter_emitter SIGABRT).  No launch
        cap can help a program that never compiles: 2^22 (4M nodes)
        is the single-chip ceiling on this toolchain.

Run:  python scripts/probe_hv_scale.py [log2_n=21] [blocks=10] [--time]
"""
import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, '.')
from partisan_tpu.config import Config
from partisan_tpu.models.hyparview_dense import (
    connectivity, dense_init, run_dense_chunked,
    run_dense_staggered_chunked)

ap = argparse.ArgumentParser()
ap.add_argument("log2_n", nargs="?", type=int, default=21)
ap.add_argument("blocks", nargs="?", type=int, default=10)
ap.add_argument("--time", action="store_true",
                help="3 timed reseeded trials after the probe")
ap.add_argument("--cap", type=int, default=None,
                help="override LAUNCH_CAP_BIG (rounds per launch)")
args = ap.parse_args()

from partisan_tpu.models import hyparview_dense as _hvd

if args.cap is not None:
    # override EVERY tier the shape could hit — rebinding only
    # LAUNCH_CAP_BIG silently ignored --cap at the 2^22+ tier
    # (launch_cap_for reads the module globals at call time)
    _hvd.LAUNCH_CAP = _hvd.LAUNCH_CAP_BIG = _hvd.LAUNCH_CAP_HUGE = \
        args.cap

cfg = Config(n_nodes=1 << args.log2_n, seed=7)
k = 5
rounds = args.blocks * 2 * k
print(f"device={jax.devices()[0]} n={cfg.n_nodes} rounds={rounds} "
      f"(chunked staggered, cap={_hvd.launch_cap_for(cfg.n_nodes)})",
      flush=True)
w = dense_init(cfg)
w.active.block_until_ready()
t0 = time.perf_counter()
w = run_dense_staggered_chunked(w, args.blocks, cfg, 0.01, k)
float(jnp.sum(w.active))
print(f"churn run: {rounds / (time.perf_counter() - t0):.1f} rounds/s "
      f"(incl. compile)", flush=True)
w = run_dense_chunked(w, 60, cfg)
float(jnp.sum(w.active))                 # sync: localize any fault here
print("heal done", flush=True)
h = {kk: float(np.asarray(v)) for kk, v in connectivity(w).items()}
print(f"health: {h}", flush=True)
if args.time:
    import statistics
    rates = []
    for t in range(3):
        w0 = dense_init(cfg.replace(seed=11 + 13 * t))
        t0 = time.perf_counter()
        out = run_dense_staggered_chunked(w0, args.blocks, cfg, 0.01, k)
        float(jnp.sum(out.active))
        rates.append(rounds / (time.perf_counter() - t0))
    print(f"median rate: {statistics.median(rates):.1f} rounds/s "
          f"({[round(r, 1) for r in rates]})", flush=True)
print("clean exit", flush=True)
