"""Compile-observatory CLI (ISSUE 14): the recompile-regression gate.

Replays the flagship entrypoint registry (``verify/lint/fingerprint.py``
— the same programs the trace-lint fingerprint gate pins structurally)
against the committed ``COMPILE_goldens.json`` and the warm
``.jax_cache``:

* ``--check`` — FAILS with a NAMED error on any lowered-module drift
  ("this program WILL recompile"), canonical-shape change, or an
  unexpected persistent-cache miss where the golden pins a hit (the
  planted-recompile case).  Wall-clock never enters the verdict, so the
  gate is CI-stable.  Every run appends its compile/cache events to
  ``COMPILE_ledger.jsonl``.
* ``--bless`` — regenerate the golden after an INTENDED program change;
  compiles each entrypoint once, which also warms the cache entry the
  new golden pins (``scripts/warm_cache.py`` is the bless-free warmer).
* ``--report`` — human summary over the accumulated ledger: top compile
  costs, cache hit rate, per-entrypoint trend across runs, and (ISSUE
  17) the AOT artifact table — ``aot_load_seconds`` vs
  ``compile_seconds`` per program, so the wall-clock the export plane
  saves is a tracked number, with the last named ``aot_stale`` reason
  per program.

The persistent-cache write thresholds are dropped to zero for the gate
process (``observatory.configure_cache``): the ``cache_misses``
monitoring event only fires when an entry is actually written, so
without this, fast recompiles would miss invisibly.

Usage:  python scripts/observatory.py --check [--entry NAME ...]
        python scripts/observatory.py --bless
        python scripts/observatory.py --report
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN = os.path.join(REPO, "COMPILE_goldens.json")
LEDGER = os.path.join(REPO, "COMPILE_ledger.jsonl")
CACHE = os.path.join(REPO, ".jax_cache")


def _jax_env() -> None:
    """8-device virtual CPU mesh, set BEFORE the first jax import (same
    setup as tests/conftest.py / scripts/trace_lint.py)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--check", action="store_true",
                   help="gate: fail on program drift or unexpected "
                        "recompile vs the committed golden")
    g.add_argument("--bless", action="store_true",
                   help="regenerate COMPILE_goldens.json (and warm the "
                        "cache entries it pins)")
    g.add_argument("--report", action="store_true",
                   help="summarize COMPILE_ledger.jsonl (no jax import)")
    ap.add_argument("--entry", action="append", default=None,
                    metavar="NAME",
                    help="restrict --check/--bless to these flagship "
                         "entrypoints (repeatable)")
    ap.add_argument("--golden", default=GOLDEN)
    ap.add_argument("--ledger", default=LEDGER)
    ap.add_argument("--cache-dir", default=CACHE)
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the --report cost table")
    args = ap.parse_args(argv)

    if args.report:
        # ledger-only: no jax, no compiles — readable anywhere
        from partisan_tpu.telemetry.observatory import (ledger_report,
                                                        read_ledger)
        if not os.path.exists(args.ledger):
            print(f"observatory: no ledger at {args.ledger} — run "
                  f"--check / --bless / warm_cache.py first",
                  file=sys.stderr)
            return 1
        print(ledger_report(read_ledger(args.ledger), top=args.top))
        return 0

    _jax_env()
    from partisan_tpu.telemetry import observatory as obs
    from partisan_tpu.verify.lint.fingerprint import FLAGSHIP

    obs.configure_cache(args.cache_dir, record_all=True)
    ledger = obs.CompileLedger(path=args.ledger, mode="a").install()

    registry = FLAGSHIP
    if args.entry:
        unknown = set(args.entry) - set(FLAGSHIP)
        if unknown:
            print(f"observatory: unknown entrypoints {sorted(unknown)}; "
                  f"known: {sorted(FLAGSHIP)}", file=sys.stderr)
            return 2
        registry = {k: FLAGSHIP[k] for k in args.entry}

    t0 = time.time()

    def progress(name):
        print(f"  {name} ... [{time.time() - t0:5.1f}s]", flush=True)

    if args.bless:
        out = obs.bless_goldens(args.golden, registry, ledger=ledger,
                                progress=progress)
        s = ledger.summary()
        for name in out:
            d = s.get(name, {})
            print(f"  blessed {name}: module={out[name]['module_hash']} "
                  f"hits={d.get('cache_hits', 0)} "
                  f"misses={d.get('cache_misses', 0)} "
                  f"compile_s={d.get('compile_s', 0.0):.2f}")
        print(f"blessed {len(out)} entrypoints -> {args.golden} "
              f"({time.time() - t0:.1f}s); ledger -> {args.ledger}")
        ledger.close()
        return 0

    if not os.path.exists(args.golden):
        print(f"observatory: missing {args.golden} — run --bless first",
              file=sys.stderr)
        return 1
    names = list(registry) if args.entry else None
    errors = obs.check_goldens(args.golden, registry, ledger=ledger,
                               compile=True, names=names,
                               progress=progress)
    summary = ledger.summary()
    gate = {n: {"hits": d["cache_hits"], "misses": d["cache_misses"],
                "compile_s": round(d["compile_s"], 2)}
            for n, d in summary.items() if n in registry}
    print(json.dumps({"gate": gate}, sort_keys=True))
    ledger.close()
    if errors:
        print(f"observatory: recompile gate FAILED ({len(errors)} "
              f"errors, {time.time() - t0:.1f}s):")
        for e in errors:
            print(f"  {e}")
        return 1
    print(f"observatory: recompile gate clean — {len(registry)} "
          f"entrypoints, every pinned program served from "
          f"{os.path.basename(args.cache_dir)} "
          f"({time.time() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
