"""Minimal reproducer for the dense-SCAMP TPU worker fault (ROADMAP 1d).

The program: models/scamp_dense.py's round (a whole-array SCAMP
subscription-walk plane) under jax.lax.scan with 1%/round churn at
N=2^16.  Observed on a v5e chip (jax 0.9.0, axon tunnel):

  * single scan of 100 rounds          -> clean, repeatedly
  * single scan of ~200 rounds         -> TPU worker crash
    ("UNAVAILABLE: TPU worker process crashed or restarted ...
    kernel fault") on the first result readback
  * the same 200-round scan on CPU     -> clean
  * N=4096, 2000-round scan on chip    -> clean
  * every constituent op of the round, run alone at shape -> clean
    (round-3 bisection, commit 18f364f)

Round-4 history (the trigger is XLA's schedule/allocation for the
whole program, not any single op):
  * restructuring the churn phase (one _spawn_walks instance per round
    instead of two) moved the failing length from ~50 to (100, 200];
  * with that mid-round-4 shape, the skip=("admit",) ablation variant
    crashed the XLA:TPU COMPILER itself — SIGABRT in
    TpuInstructionFusion::ShouldFuseInputIntoScatter,
    "scatter_emitter.cc:2824 Check failed: operand_indices.size() == 1
    (2 vs. 1)" — a second manifestation of the same fusion-machinery
    fragility at this shape;
  * the final round-4 shape (stamp-exact amortized stale-entry sweep
    replacing the full-plane scrub) runs 500-round single launches
    CLEAN at N=2^16 — but the SAME program faults the worker at
    N=2^20 on its first 100-round launch, so the bug tracks SHAPE as
    well as program structure.  make_dense_scamp_round raises a loud
    NotImplementedError for N > 2^16 on TPU devices.

This script remains the recipe and record: to reproduce, run it at
log2_n=20; if a 2^16 regression appears after a change, bisect with
make_dense_scamp_round's skip= parameter (phases: churn, admit,
inview) and scan length.  Production code chunks launches at
scamp_dense.LAUNCH_CAP=100 regardless.

Round-5 2^20 shape search (VERDICT r4 #1): the knobs below sweep the
program-shape levers that moved the 2^16 failing length in round 4 —
launch length, walker slots C, sweep width K_SWEEP, and the skip=
phase ablations.  Each variant runs in a FRESH process (one jit cache,
one worker session).

RESULTS (v5e, jax 0.9.0 axon tunnel, 2026-08-01, all at N=2^20,
churn=0.01, C=8, K_SWEEP=8 unless noted):
  * 100-round single launch            -> WORKER FAULT on first
    readback (unchanged from round 4's gate observation)
  * 25-round launches x 8  (200 rds)   -> CLEAN
  * 50-round launches x 4  (200 rds)   -> CLEAN, walker counts at
    matching round boundaries IDENTICAL to the 25-round chunking
    (chunk boundaries don't perturb the trajectory)
  * 50-round launches x 20 (1000 rds)  -> CLEAN (the soak)
So the fault tracks SINGLE-SCAN LENGTH at this shape — between 50 and
100 scanned rounds at 2^20 vs >500 at 2^16 — and chunking at
launch_cap_for(N)=50 is a complete production workaround; no C /
K_SWEEP / skip ablation was needed.  The same recipe clears the fused
plumtree plane (scripts/repro_pt_dense_fault.py: staggered 4x50
clean at 2^20 where one long scan faulted).  make_dense_scamp_round's
gate now admits N<=2^20.  Beyond: 2^21 is a MEMORY wall, not the
fault family — RESOURCE_EXHAUSTED at init (four [N, 174] int32
stamp/view planes = ~5.8 GB/state; the sweep needs two states + sort
temporaries).  Shrinking the stamp planes (uint16 wrapping rounds) is
the lever if 2M-node SCAMP is ever needed; HyParView and plumtree,
whose planes are ~6x smaller, run 2^21-2^22 (probe_hv_scale.py,
repro_pt_dense_fault.py).

Run:  python scripts/repro_scamp_dense_fault.py [rounds [log2_n]]
          [--c C] [--ksweep K] [--skip churn,admit,inview]
          [--launches L]   (L chained launches of `rounds` each,
                            exercising the LAUNCH_CAP chunking shape)
"""
import argparse
import os
import sys

# this script's PURPOSE is reproducing the fault — bypass the
# production gate (hyparview_dense.refuse_tpu_shape_bug)
os.environ["PARTISAN_TPU_UNGATE"] = "1"

import jax
import jax.numpy as jnp

sys.path.insert(0, '.')
from partisan_tpu.config import Config
from partisan_tpu.models import scamp_dense
from partisan_tpu.models.scamp_dense import (
    _run_dense_scamp_launch, dense_scamp_init)

ap = argparse.ArgumentParser()
ap.add_argument("rounds", nargs="?", type=int, default=100)
ap.add_argument("log2_n", nargs="?", type=int, default=20)
ap.add_argument("--c", type=int, default=None,
                help="walker slots (Config.scamp_walker_slots)")
ap.add_argument("--ksweep", type=int, default=None,
                help="stale-sweep columns/round (scamp_dense.K_SWEEP)")
ap.add_argument("--skip", default="",
                help="comma list of phases to ablate")
ap.add_argument("--launches", type=int, default=1,
                help="chained launches of `rounds` each")
ap.add_argument("--settle", type=int, default=0,
                help="churn-free settle rounds after the launches "
                     "(chunked via run_dense_scamp)")
ap.add_argument("--health", action="store_true",
                help="run the jitted scamp_health BFS readback at the "
                     "end (the perf-suite shape that faulted at 2^20)")
args = ap.parse_args()

if args.ksweep is not None:
    scamp_dense.K_SWEEP = args.ksweep
skip = tuple(s for s in args.skip.split(",") if s)
kw = {} if args.c is None else {"scamp_walker_slots": args.c}
cfg = Config(n_nodes=1 << args.log2_n, seed=7, **kw)
print(f"device={jax.devices()[0]} n={cfg.n_nodes} rounds={args.rounds}"
      f" launches={args.launches} C={cfg.scamp_walker_slots}"
      f" K_SWEEP={scamp_dense.K_SWEEP} skip={skip or '()'}", flush=True)
st = dense_scamp_init(cfg)
st.partial.block_until_ready()
for i in range(args.launches):
    st = _run_dense_scamp_launch(st, args.rounds, cfg, 0.01, skip)
    print(f"launch {i}: walkers={int(jnp.sum(st.walk_pos >= 0))}",
          flush=True)
if args.settle:
    from partisan_tpu.models.scamp_dense import run_dense_scamp
    st = run_dense_scamp(st, args.settle, cfg, 0.0)
    print(f"settle {args.settle}: walkers="
          f"{int(jnp.sum(st.walk_pos >= 0))}", flush=True)
if args.health:
    from partisan_tpu.models.scamp_dense import scamp_health
    h = {k: float(v) for k, v in scamp_health(st).items()}
    print("health:", h, flush=True)
print("clean exit", flush=True)
