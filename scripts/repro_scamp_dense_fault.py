"""Minimal reproducer for the dense-SCAMP TPU worker fault (ROADMAP 1d).

The program: models/scamp_dense.py's round (a whole-array SCAMP
subscription-walk plane) under jax.lax.scan with 1%/round churn at
N=2^16.  Observed on a v5e chip (jax 0.9.0, axon tunnel):

  * single scan of 100 rounds          -> clean, repeatedly
  * single scan of ~200 rounds         -> TPU worker crash
    ("UNAVAILABLE: TPU worker process crashed or restarted ...
    kernel fault") on the first result readback
  * the same 200-round scan on CPU     -> clean
  * N=4096, 2000-round scan on chip    -> clean
  * every constituent op of the round, run alone at shape -> clean
    (round-3 bisection, commit 18f364f)

Round-4 history (the trigger is XLA's schedule/allocation for the
whole program, not any single op):
  * restructuring the churn phase (one _spawn_walks instance per round
    instead of two) moved the failing length from ~50 to (100, 200];
  * with that mid-round-4 shape, the skip=("admit",) ablation variant
    crashed the XLA:TPU COMPILER itself — SIGABRT in
    TpuInstructionFusion::ShouldFuseInputIntoScatter,
    "scatter_emitter.cc:2824 Check failed: operand_indices.size() == 1
    (2 vs. 1)" — a second manifestation of the same fusion-machinery
    fragility at this shape;
  * the final round-4 shape (stamp-exact amortized stale-entry sweep
    replacing the full-plane scrub) runs 500-round single launches
    CLEAN at N=2^16 — but the SAME program faults the worker at
    N=2^20 on its first 100-round launch, so the bug tracks SHAPE as
    well as program structure.  make_dense_scamp_round raises a loud
    NotImplementedError for N > 2^16 on TPU devices.

This script remains the recipe and record: to reproduce, run it at
log2_n=20; if a 2^16 regression appears after a change, bisect with
make_dense_scamp_round's skip= parameter (phases: churn, admit,
inview) and scan length.  Production code chunks launches at
scamp_dense.LAUNCH_CAP=100 regardless.

Run:  python scripts/repro_scamp_dense_fault.py [rounds=100 [log2_n=20]]
"""
import os
import sys

# this script's PURPOSE is reproducing the fault — bypass the
# production gate (hyparview_dense.refuse_tpu_shape_bug)
os.environ["PARTISAN_TPU_UNGATE"] = "1"

import jax
import jax.numpy as jnp

sys.path.insert(0, '.')
from partisan_tpu.config import Config
from partisan_tpu.models.scamp_dense import (
    _run_dense_scamp_launch, dense_scamp_init)

rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 100
log2n = int(sys.argv[2]) if len(sys.argv) > 2 else 20
cfg = Config(n_nodes=1 << log2n, seed=7)
print(f"device={jax.devices()[0]} n={cfg.n_nodes} rounds={rounds} "
      f"(single scan launch)", flush=True)
st = dense_scamp_init(cfg)
st.partial.block_until_ready()
out = _run_dense_scamp_launch(st, rounds, cfg, 0.01, ())
print("walkers:", int(jnp.sum(out.walk_pos >= 0)), flush=True)
print("clean exit", flush=True)
