#!/usr/bin/env bash
# Run the reference's partisan_SUITE against the partisan_tpu port shim
# from a real BEAM (VERDICT r3 #7c).  This build image has no `erl`;
# run this in any environment with Erlang/OTP 24+ and rebar3:
#
#   ./scripts/ct_bridge.sh [suite-group]     # default group: default
#
# What it does:
#   1. clones/locates the reference partisan checkout (REF_DIR or the
#      rebar3 dep),
#   2. copies the shim (erlang/partisan_jax_peer_service_manager.erl)
#      into its src/ and bridge.config into its config,
#   3. points the manager at this repo's port server
#      (python -m partisan_tpu.bridge.port_server), and
#   4. runs `rebar3 ct --suite test/partisan_SUITE --group <group>`.
#
# The Python side needs only this repo on PYTHONPATH; jax runs CPU-only
# under CT (the BEAM is the driver, the simulator world is the cluster).
set -euo pipefail

GROUP="${1:-default}"
HERE="$(cd "$(dirname "$0")/.." && pwd)"
REF_DIR="${REF_DIR:-$HERE/_build_ct/partisan}"

command -v rebar3 >/dev/null || {
    echo "rebar3 not found — this harness needs a BEAM-bearing env" >&2
    exit 2
}

if [ ! -d "$REF_DIR" ]; then
    mkdir -p "$(dirname "$REF_DIR")"
    git clone --depth 1 https://github.com/lasp-lang/partisan.git "$REF_DIR"
fi

cp "$HERE/erlang/partisan_jax_peer_service_manager.erl" "$REF_DIR/src/"
mkdir -p "$REF_DIR/config"
cp "$HERE/erlang/bridge.config" "$REF_DIR/config/bridge.config"

export PYTHONPATH="$HERE${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

cd "$REF_DIR"
exec rebar3 ct --suite test/partisan_SUITE --group "$GROUP" \
    --sys_config config/bridge.config
