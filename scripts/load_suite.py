"""Capacity bench (ISSUE 8): sweep offered load through the workload
plane, locate the saturation knee, and show SLO-driven shedding holding
p99 past it.

Each sweep point runs ONE jitted scan — the offered rate is a STATE
column (``WlRow.wl_rate_milli``), so a single compiled program serves
every load point; only the shedding arm (different Config knobs) and
the sharded demo compile separately.  Measurements are window DELTAS of
the cumulative in-scan counters (the per-round stacked metrics carry
the full ``rpc_latency`` bucket family), so no mid-scan host resets are
needed: rounds ``[warm, T)`` of each scan are the measurement window.

Arms:
  * ``engine``       — unsharded ``engine.make_step``,
    ``Stacked(HyParView, Lifted(WorkloadRpc))`` at ``--n`` (default
    4096): the committed BENCH artifact's knee + p99-vs-load curve.
  * ``engine_shed``  — same, with the admission-control token bucket
    engaged (``--shed-rate`` milli-tokens/round/node): past the knee,
    p99 stays within the SLO and refusals are COUNTED in ``wl_shed``.
  * ``sharded``      — the shard_map dataplane on the 8-device virtual
    mesh (smaller N; asserts the 2-collective budget workload-on).

Usage:
    python scripts/load_suite.py                       # full bench
        [--n 4096] [--rates 1000,2000,3000,4000,6000,8000]
        [--rounds 32] [--warm 8] [--shed-rate 4000]
        [--sharded-n 512] [--skip-sharded] [--out BENCH_load.jsonl]
    python scripts/load_suite.py --smoke               # tiny tier-1 cell
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
_cache = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import partisan_tpu as pt  # noqa: E402
from partisan_tpu import peer_service as ps  # noqa: E402
from partisan_tpu.models.hyparview import HyParView  # noqa: E402
from partisan_tpu.models.stack import Lifted, Stacked  # noqa: E402
from partisan_tpu.workload import arrivals, latency  # noqa: E402
from partisan_tpu.workload.driver import WorkloadRpc  # noqa: E402

PROMISE_CAP = 16
MAX_ISSUE = 8


def make_cfg(n: int, shed_rate: int = 0, seed: int = 1) -> pt.Config:
    return pt.Config(
        n_nodes=n, seed=seed,
        # a retransmit interval above the 2-round RTT, exponential
        # backoff, bounded attempts: retries self-heal losses without a
        # same-round retransmit storm
        retransmit_interval=4, retransmit_backoff_factor=2,
        retransmit_max_attempts=3,
        slo_deadline_rounds=16,
        shed_token_rate_milli=shed_rate,
        shed_token_burst_milli=4 * max(shed_rate, 1000),
    )


def build(cfg: pt.Config, rate0: int):
    """Stacked(HyParView, Lifted(WorkloadRpc)) world, overlay pre-joined
    via the binary-tree contact pattern (scripts/chaos_soak.py)."""
    n = cfg.n_nodes
    spec = arrivals.ArrivalSpec(kind=arrivals.POISSON,
                                max_issue=MAX_ISSUE)
    drv = WorkloadRpc(cfg, promise_cap=PROMISE_CAP, spec=spec,
                      rate_milli=rate0)
    proto = Stacked(HyParView(cfg), Lifted(drv))
    world = ps.cluster(pt.init_world(cfg, proto), proto,
                       [(i, (i - 1) // 2) for i in range(1, n)])
    return proto, drv, world


def set_rate(world, drv, rate_milli: int):
    up = drv.set_rate(world.state.upper, rate_milli)
    return world.replace(state=world.state.replace(upper=up))


def measure(ms, n: int, rounds: int, warm: int, slo: int) -> dict:
    """Fold one scan's stacked per-round metrics ([T] cumulative device
    counters) into the measurement-window deltas + quantiles."""
    def col(name, idx):
        return float(np.asarray(ms[name])[idx])

    def delta(name):
        return col(name, rounds - 1) - col(name, warm - 1)

    hist = np.asarray(
        [delta(f"rpc_latency__bucket_{b}") for b in latency.BUCKET_NAMES])
    completions = float(hist.sum())
    win = rounds - warm
    q = latency.fold_quantiles(hist)
    slo_ok, slo_bad = delta("rpc_slo_ok"), delta("rpc_slo_violated")
    return {
        "completions": int(completions),
        "throughput_per_node": completions / (n * win),
        "p50": q["p50"], "p95": q["p95"], "p99": q["p99"],
        "lat_mean": (delta("rpc_latency__sum") / completions
                     if completions else None),
        "slo_ok": int(slo_ok), "slo_violated": int(slo_bad),
        "goodput_frac": (slo_ok / (slo_ok + slo_bad)
                         if slo_ok + slo_bad else None),
        "issued": int(delta("wl_issued")),
        "shed": int(delta("wl_shed")),
        "retries": int(delta("wl_retries")),
        "dead_lettered": int(delta("wl_dead_lettered")),
        "call_dropped": int(delta("rpc_call_dropped")),
        "outstanding_end": int(col("wl_outstanding", rounds - 1)),
    }


def sweep(arm: str, cfg: pt.Config, rates, rounds: int, warm: int,
          sharded: bool = False) -> list:
    n = cfg.n_nodes
    proto, drv, world = build(cfg, rates[0])
    if sharded:
        from partisan_tpu.parallel import mesh as pmesh
        from partisan_tpu.parallel.dataplane import (make_sharded_step,
                                                     place_world)
        mesh = pmesh.make_mesh()
        world = place_world(world, mesh)
        step = make_sharded_step(cfg, proto, mesh, donate=False)
        comp = step.lower(world).compile()
        st = pmesh.assert_collective_budget(
            comp, max_collectives=2, max_bytes=32 * 1024 * 1024,
            forbid=("all-gather",))
        print(f"[{arm}] collective budget workload-on: {st['counts']}")
    else:
        step = pt.make_step(cfg, proto, donate=False)

    @jax.jit
    def run_scan(w):
        return jax.lax.scan(lambda wc, _: step(wc), w, None,
                            length=rounds)

    rows = []
    for rate in rates:
        world = set_rate(world, drv, rate)
        t0 = time.perf_counter()
        world, ms = run_scan(world)
        jax.block_until_ready(world.rnd)
        dt = time.perf_counter() - t0
        row = {"bench": "load_suite", "arm": arm, "n_nodes": n,
               "rate_milli": rate, "offered_per_node": rate / 1000.0,
               "rounds": rounds, "warm": warm,
               "slo_deadline_rounds": cfg.slo_deadline_rounds,
               "shed_token_rate_milli": cfg.shed_token_rate_milli,
               **measure(ms, n, rounds, warm, cfg.slo_deadline_rounds),
               "wall_s": round(dt, 2),
               "rounds_per_sec": round(rounds / dt, 2)}
        rows.append(row)
        print(f"[{arm}] rate={rate/1000.0:.1f}/node/rnd "
              f"tput={row['throughput_per_node']:.2f} "
              f"p50={row['p50']} p99={row['p99']} "
              f"shed={row['shed']} retries={row['retries']} "
              f"({row['rounds_per_sec']} r/s)")
    return rows


def find_knee(rows, util: float = 0.85):
    """The saturation knee: the last offered rate the fabric still
    serves at >= ``util`` of offered (completions track arrivals), and
    the first rate whose p99 blows past the SLO deadline."""
    knee = None
    p99_blowup = None
    for r in rows:
        offered = r["offered_per_node"]
        if r["throughput_per_node"] >= util * offered:
            knee = r["rate_milli"]
        if p99_blowup is None and (
                math.isinf(r["p99"])
                or r["p99"] > r["slo_deadline_rounds"]):
            p99_blowup = r["rate_milli"]
    return knee, p99_blowup


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--rates", default="1000,2000,3000,4000,6000,8000")
    ap.add_argument("--rounds", type=int, default=32)
    ap.add_argument("--warm", type=int, default=8)
    ap.add_argument("--shed-rate", type=int, default=4000)
    ap.add_argument("--sharded-n", type=int, default=512)
    ap.add_argument("--skip-sharded", action="store_true")
    ap.add_argument("--skip-shed", action="store_true")
    ap.add_argument("--out", default="BENCH_load.jsonl")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny cell (n=64, 2 rates) — the tier-1 / "
                         "suite_matrix smoke configuration")
    args = ap.parse_args(argv)

    if args.smoke:
        args.n, args.rounds, args.warm = 64, 16, 4
        args.rates = "2000,8000"
        args.sharded_n = 64
        if args.out == "BENCH_load.jsonl":
            args.out = "/tmp/BENCH_load_smoke.jsonl"

    rates = [int(r) for r in args.rates.split(",") if r]
    assert args.warm >= 1 and args.rounds > args.warm

    all_rows = []
    t0 = time.perf_counter()

    base = make_cfg(args.n)
    all_rows += sweep("engine", base, rates, args.rounds, args.warm)
    knee, p99_blowup = find_knee(all_rows)
    print(f"[engine] knee={knee} p99_blowup={p99_blowup}")

    shed_rows = []
    if not args.skip_shed:
        shed_cfg = make_cfg(args.n, shed_rate=args.shed_rate)
        shed_rows = sweep("engine_shed", shed_cfg, rates, args.rounds,
                          args.warm)
        all_rows += shed_rows

    if not args.skip_sharded:
        all_rows += sweep("sharded", make_cfg(args.sharded_n),
                          rates[:4], args.rounds, args.warm,
                          sharded=True)

    # the graceful-degradation verdict: past the knee, the shed arm
    # keeps p99 within the SLO while counting refusals
    past_knee = [r for r in shed_rows
                 if knee is not None and r["rate_milli"] > knee]
    shed_holds = bool(past_knee) and all(
        not math.isinf(r["p99"])
        and r["p99"] <= r["slo_deadline_rounds"]
        and r["shed"] > 0 for r in past_knee)
    summary = {"bench": "load_suite_summary", "n_nodes": args.n,
               "knee_rate_milli": knee,
               "p99_blowup_rate_milli": p99_blowup,
               "shed_rate_milli": (None if args.skip_shed
                                   else args.shed_rate),
               "shed_holds_slo_past_knee": (None if not past_knee
                                            else shed_holds),
               "total_wall_s": round(time.perf_counter() - t0, 1)}
    all_rows.append(summary)
    print(f"summary: {summary}")

    with open(args.out, "w") as f:
        for row in all_rows:
            f.write(json.dumps(row) + "\n")
    print(f"{len(all_rows)} rows -> {args.out}")

    # unified bench ledger (ISSUE 18): one BenchRow per sweep point;
    # smoke runs land in /tmp like the legacy artifact (CI must not
    # dirty the committed trajectory)
    from partisan_tpu.telemetry import benchplane
    ledger_path = os.environ.get("PARTISAN_BENCH_LEDGER") or (
        "/tmp/BENCH_ledger_smoke.jsonl" if args.smoke else None)
    calib = benchplane.calibrate()
    benchplane.append_rows_nonfatal([benchplane.make_row(
        "load_suite", f"{r['arm']}_r{r['rate_milli']}",
        config={"rate_milli": r["rate_milli"], "warm": r["warm"],
                "slo_deadline_rounds": r["slo_deadline_rounds"]},
        n_nodes=r["n_nodes"], rounds=r["rounds"],
        rounds_per_sec=r["rounds_per_sec"], wall_s=r["wall_s"],
        calibration=calib,
        metrics={k: r[k] for k in ("throughput_per_node", "p50", "p99",
                                   "shed", "retries") if k in r})
        for r in all_rows if r.get("bench") == "load_suite"],
        ledger_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
