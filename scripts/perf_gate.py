"""Performance-observatory CLI (ISSUE 18): the perf regression gate.

The runtime twin of ``scripts/observatory.py``: where that gate pins
*compile* behavior, this one pins *throughput* and *suite runtime*
against committed goldens, and renders the cross-PR bench trajectory
from the unified ledger.

* ``--check`` — replay the CHEAP pinned subset (flagship micro-rounds
  at tier-1 shapes, AOT-loaded from ``aot_artifacts/`` so there is no
  compile wall) against ``PERF_goldens.json``.  Calibration-normalized
  rounds/sec; FAIL NAMED beyond the explicit fail band, warn-only in
  the band below it.  Then the tier-1 runtime budget: per-test
  durations in ``BENCH_suite_durations.jsonl`` vs their committed
  budgets, and the projected suite total vs the 870 s ceiling.  Every
  check appends its own measurements to ``BENCH_ledger.jsonl`` — the
  gate's runs ARE trajectory.
* ``--bless`` — regenerate ``PERF_goldens.json`` after an INTENDED perf
  change: re-measure the pinned subset and (when a durations artifact
  from a clean tier-1 run exists) regenerate the per-test budgets.
  ``--only perf`` / ``--only budget`` re-blesses one half.
* ``--report`` — the cross-suite trend table from the ledger ALONE (no
  jax import, readable anywhere).

Usage:  python scripts/perf_gate.py --check [--entry NAME ...]
        python scripts/perf_gate.py --bless [--only perf|budget]
        python scripts/perf_gate.py --report [--top N]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN = os.path.join(REPO, "PERF_goldens.json")
LEDGER = os.path.join(REPO, "BENCH_ledger.jsonl")
DURATIONS = os.path.join(REPO, "BENCH_suite_durations.jsonl")
CACHE = os.path.join(REPO, ".jax_cache")


def _jax_env() -> None:
    """8-device virtual CPU mesh, set BEFORE the first jax import (same
    setup as tests/conftest.py / scripts/observatory.py)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _benchplane_standalone():
    """Load benchplane by file path: the --report path must not import
    ``partisan_tpu`` (whose __init__ pulls the jax engine)."""
    spec = importlib.util.spec_from_file_location(
        "_benchplane_report",
        os.path.join(REPO, "partisan_tpu", "telemetry", "benchplane.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _aot_loader(ledger, cache_dir):
    """(fn, args, how) resolver for the gate: AOT artifact when present
    and signature-matched (no compile), else the builder's jitted fn
    compiled once under ledger attribution (warm-cache served)."""
    from partisan_tpu import aot

    def load(name, build):
        fn, args = build()
        prog = aot.maybe_load(name, cache_dir=cache_dir, ledger=ledger)
        if prog is not None and prog.matches(args):
            return prog, args, "aot"
        with ledger.attribute(name):
            fn.lower(*args).compile()
        return fn, args, "jit"

    return load


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--check", action="store_true",
                   help="gate: fail NAMED on a normalized rounds/sec "
                        "regression or a tier-1 runtime budget overrun")
    g.add_argument("--bless", action="store_true",
                   help="regenerate PERF_goldens.json (perf rows + "
                        "suite budgets)")
    g.add_argument("--report", action="store_true",
                   help="cross-suite trend table from BENCH_ledger.jsonl "
                        "(no jax import)")
    ap.add_argument("--entry", action="append", default=None,
                    metavar="NAME",
                    help="restrict the perf leg to these pinned subset "
                         "entries (repeatable)")
    ap.add_argument("--only", choices=["perf", "budget"], default=None,
                    help="--bless/--check one half of the golden")
    ap.add_argument("--golden", default=GOLDEN)
    ap.add_argument("--ledger", default=LEDGER,
                    help="unified bench ledger (BENCH_ledger.jsonl)")
    ap.add_argument("--durations", default=DURATIONS,
                    help="per-test durations artifact from tier-1 runs")
    ap.add_argument("--cache-dir", default=CACHE)
    ap.add_argument("--fail-pct", type=float, default=45.0,
                    help="normalized rounds/sec drop that FAILS the "
                         "gate (noise floor on a contended 1-vCPU box)")
    ap.add_argument("--warn-pct", type=float, default=18.0,
                    help="drop that warns without failing")
    ap.add_argument("--no-ledger-append", action="store_true",
                    help="do not append this run's rows to the ledger")
    ap.add_argument("--top", type=int, default=20,
                    help="series rows in the --report table")
    args = ap.parse_args(argv)

    if args.report:
        bp = _benchplane_standalone()
        if not os.path.exists(args.ledger):
            print(f"perf_gate: no ledger at {args.ledger} — run a bench "
                  f"suite or --check first", file=sys.stderr)
            return 1
        print(bp.trend_report(bp.read_bench_ledger(args.ledger),
                              top=args.top))
        return 0

    _jax_env()
    from partisan_tpu.telemetry import benchplane as bp
    from partisan_tpu.telemetry import observatory as obs
    from partisan_tpu.verify.lint.fingerprint import FLAGSHIP

    obs.configure_cache(args.cache_dir, record_all=True)
    ledger = obs.CompileLedger(
        path=os.path.join(REPO, obs.LEDGER_BASENAME), mode="a").install()
    loader = _aot_loader(ledger, args.cache_dir)

    subset = {k: v for k, v in bp.PERF_SUBSET.items() if k in FLAGSHIP}
    if args.entry:
        unknown = set(args.entry) - set(subset)
        if unknown:
            print(f"perf_gate: unknown subset entries {sorted(unknown)}; "
                  f"pinned: {sorted(subset)}", file=sys.stderr)
            return 2
        subset = {k: subset[k] for k in args.entry}

    t0 = time.time()

    def progress(name):
        print(f"  {name} ... [{time.time() - t0:5.1f}s]", flush=True)

    print(f"  calibrating ... [{time.time() - t0:5.1f}s]", flush=True)
    calib = bp.calibrate()
    print(f"  calibration score {calib['score']:.0f} "
          f"({calib['wall_s']:.1f}s)", flush=True)

    if args.bless:
        if args.only != "budget":
            golden = bp.bless_perf(args.golden, FLAGSHIP, subset,
                                   loader=loader, calibration=calib,
                                   progress=progress)
            for name, row in sorted(golden["rows"].items()):
                print(f"  blessed {name}: norm_rps={row['norm_rps']:.2f} "
                      f"raw={row['rounds_per_sec']:.1f} r/s "
                      f"spread={row['spread_pct']:.0f}% via {row['how']}")
        if args.only != "perf":
            if not os.path.exists(args.durations):
                print(f"perf_gate: no durations artifact at "
                      f"{args.durations} — run tier-1 first; budgets "
                      f"NOT blessed", file=sys.stderr)
                if args.only == "budget":
                    return 1
            else:
                if os.path.exists(args.golden):
                    with open(args.golden, encoding="utf-8") as f:
                        golden = json.load(f)
                else:
                    # --only budget on a fresh repo: start a minimal
                    # golden (perf rows land on the next full --bless)
                    golden = {"schema": bp.GOLDEN_SCHEMA,
                              "calibration": calib, "rows": {}}
                golden["suite_budget"] = bp.bless_budget(
                    args.durations, calibration=calib)
                with open(args.golden, "w", encoding="utf-8") as f:
                    json.dump(golden, f, indent=2, sort_keys=True)
                    f.write("\n")
                b = golden["suite_budget"]
                print(f"  blessed budgets: {len(b['tests'])} tests >= "
                      f"{b['floor_s']:.0f}s floor, suite total "
                      f"{b['total_s']:.0f}s vs {b['ceiling_s']:.0f}s "
                      f"ceiling")
        print(f"blessed -> {args.golden} ({time.time() - t0:.1f}s)")
        ledger.close()
        return 0

    # ----------------------------------------------------------- check
    if not os.path.exists(args.golden):
        print(f"perf_gate: missing {args.golden} — run --bless first",
              file=sys.stderr)
        return 1
    errors, warnings, rows = [], [], []
    if args.only != "budget":
        errors, warnings, rows = bp.check_perf(
            args.golden, FLAGSHIP, subset, loader=loader,
            fail_pct=args.fail_pct, warn_pct=args.warn_pct,
            calibration=calib, progress=progress)
    binfo = {}
    if args.only != "perf":
        with open(args.golden, encoding="utf-8") as f:
            golden = json.load(f)
        budget = golden.get("suite_budget")
        if budget is None:
            errors.append(
                "suite_budget: PERF GOLDEN INCOMPLETE — no committed "
                "tier-1 budgets in PERF_goldens.json; run a clean "
                "tier-1 then scripts/perf_gate.py --bless --only budget")
        elif not os.path.exists(args.durations):
            warnings.append(
                f"suite_budget: no durations artifact at "
                f"{args.durations} this run — budget gate skipped "
                f"(tier-1 writes it)")
        else:
            berr, bwarn, binfo = bp.check_budget(budget, args.durations,
                                                 calibration=calib)
            errors += berr
            warnings += bwarn
    if rows and not args.no_ledger_append:
        bp.append_rows(rows, args.ledger)
    gate = {"calib_score": calib["score"],
            "perf_rows": {r["arm"]: {"rps": r["rounds_per_sec"],
                                     "norm": r["norm_rounds_per_sec"],
                                     "how": r["metrics"]["how"]}
                          for r in rows}}
    if binfo:
        gate["budget"] = binfo
    print(json.dumps({"gate": gate}, sort_keys=True))
    ledger.close()
    for w in warnings:
        print(f"  warn: {w}")
    if errors:
        print(f"perf_gate: FAILED ({len(errors)} errors, "
              f"{time.time() - t0:.1f}s):")
        for e in errors:
            print(f"  {e}")
        return 1
    print(f"perf_gate: clean — {len(rows)} perf rows within band"
          + (f", projected tier-1 {binfo['projected_s']:.0f}s vs "
             f"{binfo['ceiling_s']:.0f}s ceiling" if binfo else "")
          + f" ({time.time() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
