"""Batched fault-space exploration campaign (ISSUE 7) — the
``bin/counterexample-find.sh`` analog, with the search batched onto the
device: B complete chaos'd executions per vmapped scan, invariants
checked in-scan, failing schedules delta-debugged in device batches and
serialized as replayable counterexample JSON.

Campaign phases (all rows append to ``BENCH_explore.jsonl``):

  1. **frontier** — run the clean AckedDelivery workload with the PR-3
     flight recorder armed; only (src, dst, typ) triples that actually
     carried traffic seed the schedule frontier
     (``explorer.frontier_from_trace``), topped up with seeded-random
     schedules (``explorer.random_frontier``).
  2. **explore** — sweep the frontier through one batched
     :class:`verify.explorer.Explorer`; the planted bug (a bounded
     retransmit budget: ``retransmit_max_attempts=2``) dead-letters
     under any drop window that outlasts the backoff schedule.
  3. **shrink + replay** — delta-debug the first counterexample to a
     minimal event table, write the JSON artifact, and verify it
     reproduces through a fresh B=1 checker (the same path
     ``scripts/chaos_soak.py --replay FILE`` drives, flight-recorder
     postmortem attached).
  4. **hyparview** — the membership-plane hunt: a standing partition
     hidden among benign perturbations violates convergence-after-heal;
     found and shrunk through a B=1 explorer (the vmapped HyParView
     program is the expensive compile on this engine — the batched
     machinery is exercised on the cheap AckedDelivery program, and the
     B=1 program is shared with tests/test_explorer.py via the
     persistent compilation cache).  Skipped under ``--smoke``.
  5. **bench** — batched-vs-serial schedules/sec on the 8-device CPU
     mesh: one ``run_batch`` of B schedules against a B=1 explorer
     looping over the same list; the batch is sharded across the mesh
     when B divides evenly.
  6. **hbbft** (ISSUE 19) — the Byzantine hunt: an equivocation +
     vote-inflation frontier against the UN-hardened hbbft worker
     violates ``no_fork`` (two halves commit different digests for one
     epoch); the find shrinks to a 1-minimal table, commits as
     ``counterexample_hbbft.json``, replays through the B=1 checker,
     and the HARDENED twin must pass the identical frontier clean.
     Runs in the full campaign and alone via ``--phase hbbft``.

Usage:
    python scripts/chaos_explore.py                   # full campaign
        [--batch 64] [--rounds 30] [--events 4] [--seed 7]
        [--out BENCH_explore.jsonl] [--counterexample-dir .]
        [--postmortem-dir /tmp] [--phase all|hbbft]
    python scripts/chaos_explore.py --smoke           # tier-1 cell
    python scripts/chaos_explore.py --phase hbbft     # Byzantine arm
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# CPU verify path + the persistent compilation cache (the vmapped
# explorer programs are compile-heavy; tests/conftest.py points at the
# same cache, so test and script runs warm each other)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(_REPO, ".jax_cache"))

import partisan_tpu as pt  # noqa: E402
from partisan_tpu import telemetry  # noqa: E402
from partisan_tpu.telemetry.flight import FlightSpec  # noqa: E402
from partisan_tpu.verify import explorer, health  # noqa: E402
from partisan_tpu.verify.chaos import ChaosSchedule  # noqa: E402
from partisan_tpu.verify.explorer import Explorer, SETUPS  # noqa: E402

ACK_N = 8
HYP_N, HYP_ROUNDS, HYP_EVENTS = 16, 60, 10
HBB_N, HBB_ROUNDS, HBB_EVENTS = 7, 12, 8


def acked_cfg(seed: int = 5) -> pt.Config:
    """The planted-bug configuration: a retransmit budget of 2 attempts
    at interval 2 with factor-2 backoff gives up inside any drop window
    longer than ~2 + 4 rounds — a dead-letter bug the explorer must
    find from traffic alone."""
    return pt.Config(n_nodes=ACK_N, inbox_cap=8, seed=seed,
                     retransmit_interval=2, retransmit_backoff_factor=2,
                     retransmit_max_attempts=2)


def record_clean_trace(cfg, proto, world, rounds: int):
    """Clean (chaos-free) run with the flight recorder armed; returns
    every decoded TraceEntry — the observed-traffic frontier source."""
    entries = []
    telemetry.run_with_telemetry(
        cfg, proto, rounds, window=rounds, world=world,
        registry=health.health_registry(),
        flight=FlightSpec(window=rounds, cap=1024),
        on_flight=lambda es: entries.extend(es))
    return entries


def acked_phase(args, rows):
    cfg = acked_cfg()
    proto, world = SETUPS["acked_uniform"](cfg)

    # -------------------------------------------------- 1. the frontier
    t0 = time.perf_counter()
    entries = record_clean_trace(cfg, proto, world, args.rounds)
    frontier = explorer.frontier_from_trace(
        entries, proto, n_rounds=args.rounds, start=1,
        window=args.rounds - 5, max_schedules=args.batch)
    n_trace = len(frontier)
    if len(frontier) < args.batch:  # seeded-random top-up
        frontier += [
            s for s in explorer.random_frontier(
                args.seed, ACK_N, args.rounds,
                count=args.batch - len(frontier),
                n_types=len(proto.msg_types))
            if not s.has_node_events]
    rows.append({
        "bench": "chaos_explore", "phase": "frontier",
        "trace_entries": len(entries), "trace_schedules": n_trace,
        "frontier": len(frontier),
        "wall_s": round(time.perf_counter() - t0, 2)})
    print(f"frontier: {len(entries)} trace entries -> {n_trace} "
          f"traffic-derived + {len(frontier) - n_trace} random "
          f"schedules")

    # ------------------------------------------- 2. the batched sweep
    ex = Explorer(cfg, proto, n_rounds=args.rounds,
                  n_events=args.events, batch=args.batch, world=world,
                  heal_margin=5)
    t0 = time.perf_counter()
    failures = ex.explore(frontier)
    sweep_s = time.perf_counter() - t0
    rows.append({
        "bench": "chaos_explore", "phase": "explore",
        "protocol": "AckedDelivery", "n": ACK_N,
        "rounds": args.rounds, "batch": args.batch,
        "frontier": len(frontier),
        "counterexamples_found": len(failures),
        "wall_s": round(sweep_s, 2)})
    print(f"explore: {len(failures)}/{len(frontier)} schedules violate "
          f"({sweep_s:.1f}s incl. compile)")
    if not failures:
        print("no counterexample found — planted bug missing?")
        return None

    # --------------------------------------- 3. shrink, write, replay
    sched, inv, first_bad = failures[0]
    t0 = time.perf_counter()
    shrunk = ex.shrink(sched, inv)
    verdict = ex.run_batch([shrunk])
    rnd = int(verdict.first_bad[0, ex.names.index(inv)])
    cx_path = os.path.join(args.counterexample_dir,
                           "counterexample_acked.json")
    explorer.write_counterexample(
        cx_path, setup="acked_uniform", cfg=cfg, sched=shrunk,
        invariant=inv, first_violation_round=rnd,
        n_rounds=args.rounds, heal_margin=5, n_events=args.events,
        original_events=len(sched.events))
    rep = explorer.replay_counterexample(
        cx_path, postmortem_dir=args.postmortem_dir)
    rows.append({
        "bench": "chaos_explore", "phase": "shrink",
        "invariant": inv, "original_events": len(sched.events),
        "shrunk_events": len(shrunk.events),
        "first_violation_round": rnd,
        "replay_reproduced": bool(rep["reproduced"]),
        "counterexample": cx_path,
        "postmortem": rep["postmortem"],
        "wall_s": round(time.perf_counter() - t0, 2)})
    print(f"shrink: {len(sched.events)} -> {len(shrunk.events)} events "
          f"({inv} @ round {rnd}); replay "
          f"{'REPRODUCED' if rep['reproduced'] else 'FAILED'} -> "
          f"{cx_path}")
    print(f"  (same replay via: python scripts/chaos_soak.py "
          f"--replay {cx_path})")
    return ex


def hyparview_phase(args, rows):
    """Membership-plane hunt on the SAME program shape as the tier-1
    parity tests (n=16, 60 rounds, 10 events, B=1) — one compile,
    shared through the persistent cache."""
    cfg = pt.Config(n_nodes=HYP_N, inbox_cap=16, shuffle_interval=5,
                    seed=3)
    proto, world = SETUPS["hyparview_tree"](cfg)
    ex = Explorer(cfg, proto, n_rounds=HYP_ROUNDS, n_events=HYP_EVENTS,
                  batch=1, world=world, heal_margin=12)
    half = HYP_N // 2
    healed = (ChaosSchedule()
              .partition(10, (0, half - 1), 1)
              .partition(10, (half, HYP_N - 1), 2).heal(24))
    noise = explorer.random_frontier(
        args.seed, HYP_N, HYP_ROUNDS, count=4,
        n_types=len(proto.msg_types), base=healed)
    planted = (ChaosSchedule().drop(3, dst=5, rounds=2)
               .delay(4, extra=1)
               .partition(6, (0, half - 1), 1))  # never healed
    frontier = [s for s in noise if not s.has_node_events] + [planted]

    t0 = time.perf_counter()
    failures = ex.explore(frontier)
    conv = [(s, n, r) for s, n, r in failures
            if n == "convergence_after_heal"]
    if not conv:
        print("hyparview: no convergence violation found")
        return
    sched, inv, rnd = conv[0]
    shrunk = ex.shrink(sched, inv)
    cx_path = os.path.join(args.counterexample_dir,
                           "counterexample_hyparview.json")
    explorer.write_counterexample(
        cx_path, setup="hyparview_tree", cfg=cfg, sched=shrunk,
        invariant=inv, first_violation_round=rnd,
        n_rounds=HYP_ROUNDS, heal_margin=12, n_events=HYP_EVENTS,
        original_events=len(sched.events))
    rep = explorer.replay_counterexample(
        cx_path, postmortem_dir=args.postmortem_dir)
    rows.append({
        "bench": "chaos_explore", "phase": "hyparview",
        "protocol": "HyParView", "n": HYP_N, "rounds": HYP_ROUNDS,
        "frontier": len(frontier),
        "counterexamples_found": len(conv),
        "invariant": inv, "original_events": len(sched.events),
        "shrunk_events": len(shrunk.events),
        "first_violation_round": rnd,
        "replay_reproduced": bool(rep["reproduced"]),
        "counterexample": cx_path,
        "wall_s": round(time.perf_counter() - t0, 2)})
    print(f"hyparview: standing partition found "
          f"({len(sched.events)} -> {len(shrunk.events)} events, "
          f"{inv} @ round {rnd}); replay "
          f"{'REPRODUCED' if rep['reproduced'] else 'FAILED'}")


def hbbft_phase(args, rows):
    """The Byzantine hunt (ISSUE 19): the frontier pairs a leader
    equivocation on ``propose`` (odd receivers store a variant batch,
    splitting the cluster's digests 4-vs-3) with duplicated-echo
    amplification over sender triples — the vote inflation that pushes
    BOTH digest camps past the n-f quorum of the un-hardened worker's
    per-message count.  The find shrinks to a 1-minimal table, commits
    as ``counterexample_hbbft.json``, replays through a fresh B=1
    checker, and the HARDENED twin (distinct-voter bitmask) must pass
    the identical frontier with ``no_fork``/``no_view_poisoning``
    green."""
    import itertools
    cfg = pt.Config(n_nodes=HBB_N, inbox_cap=HBB_N + 4, seed=11)
    proto, world = SETUPS["hbbft_unhardened"](cfg)
    ex = Explorer(cfg, proto, n_rounds=HBB_ROUNDS, n_events=HBB_EVENTS,
                  batch=8, world=world, heal_margin=2)
    t_prop = proto.typ("propose")
    frontier = [ChaosSchedule().equivocate(1, src=0, typ=t_prop)]
    for trio in itertools.combinations(range(HBB_N), 3):
        sched = ChaosSchedule().equivocate(1, src=0, typ=t_prop)
        for s in trio:
            sched = sched.duplicate(2, src=s)
        frontier.append(sched)

    t0 = time.perf_counter()
    failures = ex.explore(frontier)
    forks = [(s, n, r) for s, n, r in failures if n == "no_fork"]
    print(f"hbbft: {len(forks)}/{len(frontier)} schedules fork the "
          f"un-hardened chain")
    if not forks:
        print("hbbft: no fork found — Byzantine alphabet broken?")
        return False
    sched, inv, rnd = forks[0]
    shrunk = ex.shrink(sched, inv)
    cx_path = os.path.join(args.counterexample_dir,
                           "counterexample_hbbft.json")
    explorer.write_counterexample(
        cx_path, setup="hbbft_unhardened", cfg=cfg, sched=shrunk,
        invariant=inv, first_violation_round=rnd,
        n_rounds=HBB_ROUNDS, heal_margin=2, n_events=HBB_EVENTS,
        original_events=len(sched.events))
    rep = explorer.replay_counterexample(
        cx_path, postmortem_dir=args.postmortem_dir)

    # the hardened twin survives the whole frontier
    hproto, hworld = SETUPS["hbbft_hardened"](cfg)
    hex_ = Explorer(cfg, hproto, n_rounds=HBB_ROUNDS,
                    n_events=HBB_EVENTS, batch=8, world=hworld,
                    heal_margin=2)
    hardened_failures = hex_.explore(frontier)
    rows.append({
        "bench": "chaos_explore", "phase": "hbbft",
        "protocol": "HbbftWorker", "n": HBB_N, "rounds": HBB_ROUNDS,
        "frontier": len(frontier),
        "counterexamples_found": len(forks),
        "invariant": inv, "original_events": len(sched.events),
        "shrunk_events": len(shrunk.events),
        "first_violation_round": rnd,
        "replay_reproduced": bool(rep["reproduced"]),
        "hardened_failures": len(hardened_failures),
        "counterexample": cx_path,
        "postmortem": rep["postmortem"],
        "wall_s": round(time.perf_counter() - t0, 2)})
    print(f"hbbft: equivocation fork found "
          f"({len(sched.events)} -> {len(shrunk.events)} events, "
          f"{inv} @ round {rnd}); replay "
          f"{'REPRODUCED' if rep['reproduced'] else 'FAILED'}; "
          f"hardened twin: {len(hardened_failures)} failures over the "
          f"same frontier -> {cx_path}")
    return bool(rep["reproduced"]) and not hardened_failures


def bench_phase(args, rows, batched_ex):
    """Batched vs serial schedules/sec.  The batched explorer shards
    its inputs across the mesh when B divides the device count; the
    serial baseline re-executes the same schedules one compiled B=1
    program at a time."""
    cfg = acked_cfg()
    proto, world = SETUPS["acked_uniform"](cfg)
    B = args.batch
    mesh = None
    if B % len(jax.devices()) == 0:
        mesh = jax.make_mesh((len(jax.devices()),), ("b",))
    ex = Explorer(cfg, proto, n_rounds=args.rounds,
                  n_events=args.events, batch=B, world=world,
                  heal_margin=5, mesh=mesh) if mesh is not None \
        else batched_ex
    scheds = [s for s in explorer.random_frontier(
        args.seed + 1, ACK_N, args.rounds, count=B + 8,
        n_types=len(proto.msg_types)) if not s.has_node_events][:B]
    scheds += [ChaosSchedule().drop(1, dst=1, rounds=2)] \
        * (B - len(scheds))

    ex.run_batch(scheds)  # compile + warm
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        ex.run_batch(scheds)
    batched_s = (time.perf_counter() - t0) / reps
    batched_sps = B / batched_s

    serial = Explorer(cfg, proto, n_rounds=args.rounds,
                      n_events=args.events, batch=1, world=world,
                      heal_margin=5)
    serial.run_batch(scheds[:1])  # compile + warm
    t0 = time.perf_counter()
    for s in scheds:
        serial.run_batch([s])
    serial_s = time.perf_counter() - t0
    serial_sps = B / serial_s

    rows.append({
        "bench": "chaos_explore", "phase": "bench",
        "protocol": "AckedDelivery", "n": ACK_N,
        "rounds": args.rounds, "batch": B,
        "devices": len(jax.devices()),
        "sharded": mesh is not None,
        "batched_s": round(batched_s, 4),
        "serial_s": round(serial_s, 4),
        "batched_schedules_per_sec": round(batched_sps, 2),
        "serial_schedules_per_sec": round(serial_sps, 2),
        "speedup": round(batched_sps / serial_sps, 2)})
    print(f"bench: batched {batched_sps:.1f} sched/s vs serial "
          f"{serial_sps:.1f} sched/s -> {batched_sps / serial_sps:.1f}x "
          f"(B={B}, sharded={mesh is not None})")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--rounds", type=int, default=30)
    ap.add_argument("--events", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--out", default="BENCH_explore.jsonl")
    ap.add_argument("--counterexample-dir", default=".")
    ap.add_argument("--postmortem-dir", default="/tmp")
    ap.add_argument("--smoke", action="store_true",
                    help="small batch, AckedDelivery phases only — the "
                         "tier-1 smoke configuration")
    ap.add_argument("--phase", choices=("all", "hbbft"), default="all",
                    help="'hbbft' runs only the Byzantine arm "
                         "(ISSUE 19)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.batch = 8

    os.makedirs(args.counterexample_dir, exist_ok=True)
    rows = []

    if args.phase == "hbbft":
        ok = hbbft_phase(args, rows)
        with open(args.out, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        print(f"\n{len(rows)} rows -> {args.out}")
        return 0 if ok else 1

    batched_ex = acked_phase(args, rows)
    if batched_ex is None:
        return 1
    if not args.smoke:
        hyparview_phase(args, rows)
        hbbft_phase(args, rows)
    bench_phase(args, rows, batched_ex)

    with open(args.out, "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    print(f"\n{len(rows)} rows -> {args.out}")
    shr = [r for r in rows if r["phase"] in ("shrink", "hyparview",
                                             "hbbft")]
    return 0 if shr and all(r["replay_reproduced"] for r in shr) else 1


if __name__ == "__main__":
    sys.exit(main())
