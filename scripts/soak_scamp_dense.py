"""Chip soak for the dense-SCAMP 2^16 program (ROADMAP 1d repro/fix
surface).  Round 3's program reproducibly faulted the TPU worker beyond
~50 scanned rounds at N=2^16 with churn enabled; round 4 restructured
the churn phase (one _spawn_walks per round).  This script runs the
restructured program for SOAK rounds in scanned chunks, printing health
after each chunk, then times a measurement pass.

Usage: python scripts/soak_scamp_dense.py [log2_n] [soak_rounds]
"""
import sys, time
import jax
import jax.numpy as jnp

sys.path.insert(0, '.')
from partisan_tpu.config import Config
from partisan_tpu.models.scamp_dense import (
    dense_scamp_init, run_dense_scamp, scamp_health)

log2n = int(sys.argv[1]) if len(sys.argv) > 1 else 16
soak = int(sys.argv[2]) if len(sys.argv) > 2 else 500
n = 1 << log2n
cfg = Config(n_nodes=n, seed=7)
print(f"device={jax.devices()[0]} n={n} soak={soak}", flush=True)

t0 = time.time()
st = dense_scamp_init(cfg)
st.partial.block_until_ready()
print(f"init {time.time()-t0:.1f}s", flush=True)

chunk = 100
t0 = time.time()
done = 0
while done < soak:
    st = run_dense_scamp(st, chunk, cfg, 0.01)
    # sync on a scalar readback (tunnel block_until_ready can return early)
    w = int(jnp.sum(st.walk_pos >= 0))
    done += chunk
    print(f"  rounds={done} walkers={w} t={time.time()-t0:.1f}s", flush=True)
h = {k: v.item() if hasattr(v, 'item') else v
     for k, v in scamp_health(run_dense_scamp(st, 60, cfg)).items()}
print("health:", h, flush=True)

# timed pass: warm compile already done; median-of-3 with distinct inputs
times = []
for i in range(3):
    s0 = dense_scamp_init(Config(n_nodes=n, seed=100 + i))
    s0.partial.block_until_ready()
    t0 = time.time()
    out = run_dense_scamp(s0, 200, cfg, 0.01)
    _ = int(jnp.sum(out.walk_pos >= 0))
    times.append(time.time() - t0)
times.sort()
rps = 200 / times[1]
print(f"timed: {times} median rounds/s={rps:.1f}", flush=True)
