"""Differential profiling for the generic engine (ROADMAP #1/#3).

Times one steady-state round of a protocol under ablations that isolate
each engine phase, so the dominant cost is located by subtraction rather
than guessed:

  default       the full step as configured
  inbox_K/4     deliver loop scaled down (K x types gating cost)
  null_handlers handlers return (row, no_emit) — framework minus protocol
  node_cap      per-node emission pre-compaction before the global sort
  gather_G      sparse delivery gather
  out_cap/4     the global compact + route sort at a smaller carry

Usage: python scripts/profile_engine.py [--proto scamp_v2|hyparview]
       [--n 1024] [--rounds 20] [--warm 40]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

import partisan_tpu as pt  # noqa: E402
from partisan_tpu import peer_service  # noqa: E402
from partisan_tpu.engine import default_out_cap, init_world, make_step  # noqa: E402


def build(cfg, proto_name):
    if proto_name == "scamp_v2":
        from partisan_tpu.models.scamp import ScampV2
        return ScampV2(cfg)
    if proto_name == "hyparview":
        from partisan_tpu.models.hyparview import HyParView
        return HyParView(cfg)
    raise ValueError(proto_name)


def null_wrap(proto):
    """Replace every handler body with identity (same emission SHAPES so
    the collect path is unchanged) — what's left is the engine frame."""
    class Null(type(proto)):
        def handlers(self):
            def h(cfg, me, row, m, key):
                return row, self.no_emit()
            return tuple(h for _ in self.msg_types)

        def tick(self, cfg, me, row, rnd, key):
            return row, self.no_emit(self.tick_emit_cap)
    n = object.__new__(Null)
    n.__dict__.update(proto.__dict__)
    return n


def timed(cfg, proto, world, rounds, label, out_cap=None):
    step = make_step(cfg, proto, donate=False, out_cap=out_cap)
    w, m = step(world)                      # compile
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    w = world
    for _ in range(rounds):
        w, m = step(w)
    jax.block_until_ready(m)
    dt = (time.perf_counter() - t0) / rounds
    print(f"{label:24s} {dt * 1e3:9.1f} ms/round   "
          f"({1 / dt:7.1f} rounds/s)")
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--proto", default="scamp_v2")
    ap.add_argument("--n", type=int, default=1024)
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--warm", type=int, default=40)
    args = ap.parse_args()

    def mkcfg(**kw):
        base = dict(n_nodes=args.n, inbox_cap=16, periodic_interval=5)
        base.update(kw)
        return pt.Config(**base)

    cfg = mkcfg()
    proto = build(cfg, args.proto)
    world = init_world(cfg, proto)
    world = peer_service.cluster(
        world, proto, [(i, 0) for i in range(1, args.n)], stagger=8)
    warm_step = make_step(cfg, proto, donate=False)
    for _ in range(args.warm):
        world, _ = warm_step(world)         # steady-state world
    jax.block_until_ready(world.msgs.valid)
    print(f"proto={args.proto} N={args.n} "
          f"out_cap={default_out_cap(cfg, proto)} "
          f"K={cfg.inbox_cap} E={proto.emit_cap} T={proto.tick_emit_cap} "
          f"types={len(proto.msg_types)} "
          f"inflight={int(world.msgs.count())}")

    timed(cfg, proto, world, args.rounds, "default")
    timed(cfg, proto, world, args.rounds, "out_cap/4",
          out_cap=default_out_cap(cfg, proto) // 4)
    timed(cfg, null_wrap(proto), world, args.rounds, "null_handlers")

    cfg4 = mkcfg(inbox_cap=4)
    p4 = build(cfg4, args.proto)
    w4 = jax.tree_util.tree_map(lambda x: x, world)
    timed(cfg4, p4, w4, args.rounds, "inbox_K=4")

    cfgn = mkcfg(node_emit_cap=8)
    timed(cfgn, build(cfgn, args.proto), world, args.rounds,
          "node_emit_cap=8")

    cfgg = mkcfg(deliver_gather_cap=32)
    timed(cfgg, build(cfgg, args.proto), world, args.rounds,
          "gather_G=32")

    cfgng = mkcfg(node_emit_cap=8, deliver_gather_cap=32)
    timed(cfgng, build(cfgng, args.proto), world, args.rounds,
          "node_cap+gather")


if __name__ == "__main__":
    main()
