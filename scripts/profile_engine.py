"""Differential profiling for the generic engine (ROADMAP #1/#3).

Times steady-state rounds of a protocol under ablations that isolate
each engine phase, so the dominant cost is located by subtraction rather
than guessed.  Variants (see `variants` in main): default, out_cap/16,
null_handlers (framework minus protocol), inbox_K=8, node_emit_cap=8
(running-offset collect), gather_G=32 (chunked delivery),
node_cap+gather, ncap+gath+cap/16, null+ncap+gather, ncap32+gather.

Each variant builds its OWN steady state (carry shape depends on the
config) and syncs with SCALAR READBACKS — block_until_ready does not
reliably block on this box (see the tpu-tunnel-measurement notes; also:
run under jax.config.update("jax_platforms", "cpu") if you want CPU —
the env var alone is ignored by the image's TPU plugin).

Usage: python scripts/profile_engine.py [--proto scamp_v2|hyparview]
       [--n 1024] [--rounds 10] [--warm 30] [--only SUBSTR]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

import partisan_tpu as pt  # noqa: E402
from partisan_tpu import peer_service  # noqa: E402
from partisan_tpu.engine import default_out_cap, init_world, make_step  # noqa: E402


def build(cfg, proto_name):
    if proto_name == "scamp_v2":
        from partisan_tpu.models.scamp import ScampV2
        return ScampV2(cfg)
    if proto_name == "hyparview":
        from partisan_tpu.models.hyparview import HyParView
        return HyParView(cfg)
    raise ValueError(proto_name)


def null_wrap(proto):
    """Replace every handler body with identity (same emission SHAPES so
    the collect path is unchanged) — what's left is the engine frame."""
    class Null(type(proto)):
        def handlers(self):
            def h(cfg, me, row, m, key):
                return row, self.no_emit()
            return tuple(h for _ in self.msg_types)

        def tick(self, cfg, me, row, rnd, key):
            return row, self.no_emit(self.tick_emit_cap)
    n = object.__new__(Null)
    n.__dict__.update(proto.__dict__)
    return n


def timed(cfg, proto_name, warm, rounds, label, out_cap=None,
          null_handlers=False):
    """Build the variant's OWN steady state (worlds are not portable
    across configs: out_cap is part of the carry shape) and time with a
    sync every round (async dispatch otherwise hides per-round cost)."""
    proto = build(cfg, proto_name)
    if null_handlers:
        proto = null_wrap(proto)
    world = init_world(cfg, proto, out_cap=out_cap)
    world = peer_service.cluster(
        world, proto, [(i, 0) for i in range(1, cfg.n_nodes)], stagger=8)
    step = make_step(cfg, proto, donate=False, out_cap=out_cap)
    t0 = time.perf_counter()
    m = None
    for _ in range(warm):
        world, m = step(world)
    if m is None:
        world, m = step(world)
    int(m["delivered"])
    warm_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(rounds):
        world, m = step(world)
        # block_until_ready can return before execution completes under
        # this runtime (memory: tpu-tunnel-measurement); only a scalar
        # READBACK reliably syncs.  Read one late output (the compacted
        # carry) plus a state leaf.
        int(world.msgs.valid.sum())
        int(jax.tree_util.tree_leaves(world.state)[0].sum())
    dt = (time.perf_counter() - t0) / rounds
    print(f"{label:24s} {dt * 1e3:9.1f} ms/round  ({1 / dt:7.1f} r/s)  "
          f"[warm+compile {warm_dt:.0f}s, "
          f"inflight {int(world.msgs.count())}, "
          f"delivered/rnd {int(m['delivered'])}, "
          f"dropped {int(m['out_dropped'])}]")
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--proto", default="scamp_v2")
    ap.add_argument("--n", type=int, default=1024)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--warm", type=int, default=30)
    ap.add_argument("--only", default=None)
    args = ap.parse_args()

    def mkcfg(**kw):
        base = dict(n_nodes=args.n, inbox_cap=16, periodic_interval=5)
        base.update(kw)
        return pt.Config(**base)

    cfg = mkcfg()
    proto = build(cfg, args.proto)
    print(f"proto={args.proto} N={args.n} "
          f"out_cap={default_out_cap(cfg, proto)} "
          f"K={cfg.inbox_cap} E={proto.emit_cap} T={proto.tick_emit_cap} "
          f"types={len(proto.msg_types)}")

    variants = [
        ("default", {}, {}),
        ("out_cap/16", {}, {"out_cap": default_out_cap(cfg, proto) // 16}),
        ("null_handlers", {}, {"null_handlers": True}),
        ("inbox_K=8", {"inbox_cap": 8}, {}),
        ("node_emit_cap=8", {"node_emit_cap": 8}, {}),
        ("gather_G=32", {"deliver_gather_cap": 32}, {}),
        ("node_cap+gather", {"node_emit_cap": 8,
                             "deliver_gather_cap": 32}, {}),
        ("ncap+gath+cap/16", {"node_emit_cap": 8,
                              "deliver_gather_cap": 32},
         {"out_cap": default_out_cap(cfg, proto) // 16}),
        ("null+ncap+gather", {"node_emit_cap": 8,
                              "deliver_gather_cap": 32},
         {"null_handlers": True}),
        ("ncap32+gather", {"node_emit_cap": 32,
                           "deliver_gather_cap": 32}, {}),
    ]
    for label, cfg_kw, t_kw in variants:
        if args.only and args.only not in label:
            continue
        timed(mkcfg(**cfg_kw), args.proto, args.warm, args.rounds,
              label, **t_kw)


if __name__ == "__main__":
    main()
