"""Flight-trace summarizer: top talkers, per-type counts, inter-shard
traffic matrix.

Consumes a wire trace in the JSONL format both recorder paths persist
(``verify.trace.write_trace`` — one ``{"rnd", "src", "dst", "typ",
"channel", "hash"}`` object per line, the dets-trace-file analog) and
prints ONE JSON summary line, plus an optional human-readable table on
stderr with ``--pretty``:

  * ``top_talkers`` / ``top_listeners`` — the N sources/destinations by
    message count (the hotspot view: a join-storm contact or a
    plumtree root shows up immediately);
  * ``per_typ`` — message count by wire tag (pass ``--typ-names`` to
    label them, e.g. the protocol's ``msg_types`` joined by commas);
  * ``intershard`` — the [D, D] src-shard x dst-shard traffic matrix
    for ``--shards D`` (rows = sender shard): the dataplane's
    all_to_all load picture — off-diagonal mass is cross-chip traffic,
    the diagonal stays on-device.

``--message SRC,SEQ`` reports ONE message instead of the summary: every
wire hop carrying that trace id (``seq`` is the tracer's int32 stamp —
the signed bitcast of the entry hash, the convention
``telemetry.tracer.wire_deliveries`` pins), oldest first.

Run:  python scripts/flight_report.py TRACE.jsonl [--shards 8]
          [--nodes N] [--top 10] [--typ-names a,b,c] [--pretty]
          [--message 3,-123456789]
"""

import argparse
import collections
import json
import sys

sys.path.insert(0, ".")  # run from the repo root

from partisan_tpu.verify.trace import read_trace  # noqa: E402


def summarize(entries, n_shards=1, n_nodes=None, top=10, typ_names=None):
    if n_nodes is None:
        n_nodes = 1 + max((max(e.src, e.dst) for e in entries),
                          default=0)
    n_loc = max(1, -(-n_nodes // n_shards))

    def shard_of(node):
        return min(max(node, 0) // n_loc, n_shards - 1)

    talkers = collections.Counter(e.src for e in entries)
    listeners = collections.Counter(e.dst for e in entries)
    per_typ = collections.Counter(e.typ for e in entries)
    rounds = sorted({e.rnd for e in entries})
    mat = [[0] * n_shards for _ in range(n_shards)]
    for e in entries:
        mat[shard_of(e.src)][shard_of(e.dst)] += 1
    cross = sum(mat[i][j] for i in range(n_shards)
                for j in range(n_shards) if i != j)

    def typ_label(t):
        if typ_names is not None and 0 <= t < len(typ_names):
            return typ_names[t]
        return str(t)

    return {
        "entries": len(entries),
        "rounds": len(rounds),
        "round_span": [rounds[0], rounds[-1]] if rounds else [],
        "msgs_per_round": round(len(entries) / len(rounds), 2)
        if rounds else 0.0,
        "top_talkers": talkers.most_common(top),
        "top_listeners": listeners.most_common(top),
        "per_typ": {typ_label(t): c
                    for t, c in sorted(per_typ.items())},
        "shards": n_shards,
        "intershard": mat,
        "cross_shard_frac": round(cross / len(entries), 4)
        if entries else 0.0,
    }


def signed_seq(h):
    """Entry hash (uint32) -> the tracer's int32 seq stamp (value-
    preserving bitcast — telemetry.tracer.wire_deliveries)."""
    h = int(h) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


def message_report(entries, src, seq, typ_names=None):
    """Every wire hop carrying trace id (src, seq), oldest first."""
    def typ_label(t):
        if typ_names is not None and 0 <= t < len(typ_names):
            return typ_names[t]
        return t
    hops = sorted((e for e in entries
                   if e.src == src and signed_seq(e.hash) == seq),
                  key=lambda e: (e.rnd, e.dst))
    return {
        "src": src, "seq": seq, "found": bool(hops), "hops": len(hops),
        "round_span": [hops[0].rnd, hops[-1].rnd] if hops else [],
        "path": [{"rnd": e.rnd, "dst": e.dst, "typ": typ_label(e.typ),
                  "channel": e.channel} for e in hops],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="wire-trace JSONL (write_trace format)")
    ap.add_argument("--shards", type=int, default=1)
    ap.add_argument("--nodes", type=int, default=None,
                    help="node count (default: inferred from max id)")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--typ-names", default=None,
                    help="comma-separated wire-tag names")
    ap.add_argument("--pretty", action="store_true",
                    help="human-readable table on stderr")
    ap.add_argument("--message", default=None, metavar="SRC,SEQ",
                    help="report one message's wire hops (tracer id)")
    args = ap.parse_args()

    entries = read_trace(args.trace)
    typ_names = args.typ_names.split(",") if args.typ_names else None
    if args.message is not None:
        src, seq = (int(x) for x in args.message.split(","))
        m = message_report(entries, src, seq, typ_names=typ_names)
        print(json.dumps(m))
        if not m["found"]:
            sys.exit(1)
        return
    s = summarize(entries, n_shards=args.shards, n_nodes=args.nodes,
                  top=args.top, typ_names=typ_names)
    print(json.dumps(s))

    if args.pretty:
        p = lambda *a: print(*a, file=sys.stderr)
        p(f"{s['entries']} messages over {s['rounds']} rounds "
          f"(span {s['round_span']}, {s['msgs_per_round']}/round)")
        p("top talkers:   "
          + ", ".join(f"{n}({c})" for n, c in s["top_talkers"]))
        p("top listeners: "
          + ", ".join(f"{n}({c})" for n, c in s["top_listeners"]))
        p("per type:      "
          + ", ".join(f"{t}={c}" for t, c in s["per_typ"].items()))
        if args.shards > 1:
            p(f"inter-shard matrix (cross-shard "
              f"{100 * s['cross_shard_frac']:.1f}%):")
            for row in s["intershard"]:
                p("  " + " ".join(f"{c:7d}" for c in row))


if __name__ == "__main__":
    main()
