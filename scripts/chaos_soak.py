"""Chaos campaign runner (ISSUE 4) — the seed x fault-mix matrix that
closes the chaos-plane loop.

Each CELL compiles one :class:`verify.chaos.ChaosSchedule` into the
engine round (``make_step(chaos=)``), runs it under the in-scan health
plane (``verify.health.health_registry`` through the PR-1 telemetry
ring) with the PR-3 flight recorder armed, and asserts
**convergence-after-heal**: the partition-aware connectivity proxy
(``health_reach_frac``) must return to 1.0 within ``--heal-margin``
rounds of the schedule's last heal/recover event and STAY there to the
end of the run.  Every cell appends one JSONL row to ``BENCH_chaos.jsonl``
(seed, mix, chaos counters, watermark, converged round, verdict); a
failing cell additionally dumps a flight-recorder POSTMORTEM — the last
recorded window's wire trace (``verify.trace.write_trace`` format) —
and records its path in the row.

This is the fault-injection analog of the reference's
``partisan_trace_orchestrator`` + crash_fault_model campaigns
(prop_partisan), with the orchestrator compiled away: fault schedules
are data, the health monitors run in-scan, and the soak only touches
the host once per window.

``--checkpoint DIR`` saves the campaign state after every cell through
the shard-aware :mod:`partisan_tpu.checkpoint` (the finished cell's
world + a ``completed``/``rows`` ledger in the manifest's ``extra``);
``--resume`` restores the ledger, integrity-checks the saved world
against its own config, and continues from the first unfinished cell —
the resumed ``BENCH_chaos.jsonl`` is row-identical to an uninterrupted
run (modulo wall-clock fields).

``--replay FILE`` re-executes a fault-space counterexample artifact
(``verify.explorer.write_counterexample`` / scripts/chaos_explore.py)
through the B=1 vmapped checker and attaches a flight-recorder
postmortem — the ``bin/counterexample-replay.sh`` analog.

Usage:
    python scripts/chaos_soak.py                      # full campaign
        [--n 4096] [--rounds 160] [--window 32]
        [--seeds 1,2,3,4] [--mixes crash_recover,partition_heal,lossy_combo]
        [--heal-margin 60] [--out BENCH_chaos.jsonl]
        [--flight-cap 2048] [--postmortem-dir /tmp]
        [--checkpoint DIR] [--resume]
    python scripts/chaos_soak.py --smoke              # one tiny cell
    python scripts/chaos_soak.py --replay cx.json     # counterexample
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU verify path (the real chip stays free for bench.py) — the same
# env + config dance as suite_matrix.py / tests/conftest.py
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import partisan_tpu as pt  # noqa: E402
from partisan_tpu import checkpoint  # noqa: E402
from partisan_tpu import peer_service as ps  # noqa: E402
from partisan_tpu import telemetry  # noqa: E402
from partisan_tpu.models.hyparview import HyParView  # noqa: E402
from partisan_tpu.telemetry.flight import FlightSpec, flight_entries  # noqa: E402
from partisan_tpu.verify import trace as trace_mod  # noqa: E402
from partisan_tpu.verify.chaos import ChaosSchedule  # noqa: E402
from partisan_tpu.verify.latency import LatencyPlane  # noqa: E402
from partisan_tpu.verify import health  # noqa: E402


# ------------------------------------------------------------- fault mixes
#
# Each mix maps (n, rounds) -> ChaosSchedule.  Events scale with the
# run: disruption in the first half, heal/recover by ~60%, leaving the
# tail to re-knit.  Mixes are seed-independent (the seed varies the
# PROTOCOL trajectory; the schedule is the controlled variable).

def _mix_crash_recover(n: int, rounds: int) -> ChaosSchedule:
    """Crash 1/8 of the cluster mid-bootstrap, recover later."""
    q = rounds // 4
    lo, hi = n // 4, n // 4 + n // 8 - 1
    return (ChaosSchedule()
            .crash(q, (lo, hi))
            .recover(2 * q + q // 2, (lo, hi)))


def _mix_partition_heal(n: int, rounds: int) -> ChaosSchedule:
    """Split the cluster into halves, heal at ~60%."""
    q = rounds // 4
    return (ChaosSchedule()
            .partition(q, (0, n // 2 - 1), 1)
            .partition(q, (n // 2, n - 1), 2)
            .heal(2 * q + q // 2))


def _mix_lossy_combo(n: int, rounds: int) -> ChaosSchedule:
    """Everything at once: a crashed block inside a partitioned half,
    a lossy window, delays and duplication — the kitchen-sink cell."""
    q = rounds // 4
    return (ChaosSchedule()
            .partition(q, (0, n // 2 - 1), 1)
            .partition(q, (n // 2, n - 1), 2)
            .crash(q + 2, (n // 8, n // 8 + n // 16 - 1))
            .drop(q + 4, dst=7, rounds=q)          # one victim's inbox
            .delay(q + 6, src=3, extra=2)
            .duplicate(q + 8, copy_delay=1)
            .heal(2 * q + q // 2)
            .recover(2 * q + q // 2 + 2,
                     (n // 8, n // 8 + n // 16 - 1)))


def _mix_byzantine_combo(n: int, rounds: int) -> ChaosSchedule:
    """The Byzantine alphabet (ISSUE 19) riding the partition scaffold:
    equivocated and replayed keepalives, a corrupting relay, a forged
    neighbor claim and duplicated traffic — all inside the partitioned
    window, healing at ~60% so convergence-after-heal still gates the
    cell.  Wire types are HyParView's (keepalive=9, neighbor=2).
    Keepalives are emitted on even rounds (keepalive_interval=2), so
    they sit in the ready buffer on ODD rounds — the keepalive-matching
    events pin odd rounds or every campaign scale where q+k lands even
    would count zero (the smoke scale only hit by parity luck)."""
    q = rounds // 4
    ka1 = (q + 2) | 1          # odd: keepalives in the ready buffer
    ka2 = ka1 + 2
    return (ChaosSchedule()
            .partition(q, (0, n // 2 - 1), 1)
            .partition(q, (n // 2, n - 1), 2)
            .equivocate(ka1, typ=9, salt=3)
            .corrupt(q + 3, salt=5)
            .replay(ka2, typ=9, after=3)
            .forge(q + 5, src=3, dst=11, typ=2)
            .duplicate(q + 6, src=4)
            .heal(2 * q + q // 2))


MIXES = {
    "crash_recover": _mix_crash_recover,
    "partition_heal": _mix_partition_heal,
    "lossy_combo": _mix_lossy_combo,
    # the WAN cells (ISSUE 19) run the partition_heal schedule under a
    # LATENCY plane — same disruption, geo-distributed delivery
    "byzantine_combo": _mix_byzantine_combo,
    "wan_1": _mix_partition_heal,
    "wan_20": _mix_partition_heal,
    "wan_100": _mix_partition_heal,
}


def _wan_plane(n: int, rtt_rounds: int) -> LatencyPlane:
    """Two-region halves with a cross-region RTT of ``rtt_rounds`` —
    the netem sweep's topology (SURVEY §6: RTT in {1, 20, 100} ms at
    ~10 ms/round)."""
    return LatencyPlane(regions=(0,) * (n // 2) + (1,) * (n - n // 2),
                        base_rtt=((0, rtt_rounds), (rtt_rounds, 0)),
                        jitter_milli=50, seed=19)


# mix -> latency-plane builder (None = no plane); rtt_rounds =
# ceil(ms / 10) at the simulator's ~10 ms-per-round calibration
LATENCY = {
    "wan_1": lambda n: _wan_plane(n, 1),
    "wan_20": lambda n: _wan_plane(n, 2),
    "wan_100": lambda n: _wan_plane(n, 10),
}


class _Rows:
    """Sink capturing ring rows on the host (per-cell, bounded)."""

    def __init__(self):
        self.rows = []

    def write_row(self, row):
        self.rows.append(row)

    def close(self):
        pass


def live_stream(window: int):
    """Mid-scan heartbeat for ``--stream`` (ISSUE 14): every round's
    metric row drains to the host through the ordered ``io_callback``
    while the scan is still running, and one line prints per window —
    a soak that wedges mid-scan now shows WHERE.  The streaming program
    embeds a host callback, so it is never persistently cacheable; each
    --stream cell pays its own compile (the documented trade)."""
    from partisan_tpu.telemetry import StreamSpec

    def on_row(row):
        rnd = row.get("round")
        if rnd is None or int(rnd) % max(window, 1):
            return
        reach = row.get("health_reach_frac")
        extra = f" reach={reach:.3f}" if reach is not None else ""
        print(f"    [stream] round {int(rnd)}{extra}", flush=True)

    return StreamSpec(on_row=on_row)


def run_cell(*, n: int, rounds: int, seed: int, mix: str, window: int,
             heal_margin: int, flight_cap: int, postmortem_dir: str,
             shuffle_interval: int = 5, stream=None,
             out: dict = None) -> dict:
    """Run one (seed, mix) cell; returns its JSONL row (a plain dict).

    ``out``, when given, receives the cell's final ``world`` and ``cfg``
    so the campaign loop can checkpoint them (--checkpoint/--resume)."""
    sched = MIXES[mix](n, rounds)
    plane = LATENCY[mix](n) if mix in LATENCY else None
    heal_rnd = sched.last_heal_round()
    cfg = pt.Config(n_nodes=n, inbox_cap=16,
                    shuffle_interval=shuffle_interval, seed=seed)
    proto = HyParView(cfg)
    # binary-tree contacts spread the join storm (each contact takes at
    # most 2 joins) so the overlay is connected within a few rounds even
    # at N=4096 — a chain + trickle bootstrap would still be injecting
    # joins when the chaos events fire (scripts/bench_telemetry.py uses
    # the same shape)
    world = ps.cluster(pt.init_world(cfg, proto), proto,
                       [(i, (i - 1) // 2) for i in range(1, n)])
    registry = health.health_registry()
    sink = _Rows()
    last_window = {"entries": None}

    def on_flight(entries):
        last_window["entries"] = entries  # keep only the latest window

    t0 = time.perf_counter()
    world, timeline = telemetry.run_with_telemetry(
        cfg, proto, rounds, window=window, registry=registry,
        sinks=[sink], world=world,
        flight=FlightSpec(window=window, cap=flight_cap),
        on_flight=on_flight, stream=stream,
        step_kw=({"chaos": sched} if plane is None
                 else {"chaos": sched, "latency": plane}))
    dt = time.perf_counter() - t0
    if out is not None:
        out["world"], out["cfg"] = world, cfg

    rows = [r for r in sink.rows if "health_reach_frac" in r]
    conv = health.converged_round(rows, after=heal_rnd)
    ok = conv is not None and (conv - heal_rnd) <= heal_margin
    row = {
        "bench": "chaos_soak",
        "mix": mix,
        "seed": seed,
        "n_nodes": n,
        "rounds": rounds,
        "heal_round": heal_rnd,
        "converged_round": conv,
        "heal_margin": heal_margin,
        "converged": bool(ok),
        "final_reach_frac": rows[-1]["health_reach_frac"] if rows else None,
        "final_alive": rows[-1]["alive"] if rows else None,
        "chaos_dropped": sum(r.get("chaos_dropped", 0) for r in rows),
        "chaos_delayed": sum(r.get("chaos_delayed", 0) for r in rows),
        "chaos_duplicated": sum(r.get("chaos_duplicated", 0)
                                for r in rows),
        "chaos_equivocated": sum(r.get("chaos_equivocated", 0)
                                 for r in rows),
        "chaos_forged": sum(r.get("chaos_forged", 0) for r in rows),
        "chaos_replayed": sum(r.get("chaos_replayed", 0) for r in rows),
        "chaos_corrupted": sum(r.get("chaos_corrupted", 0) for r in rows),
        "wan_rtt_rounds": (int(plane.base_rtt[0][1])
                           if plane is not None else None),
        "fault_dropped": sum(r.get("fault_dropped", 0) for r in rows),
        "inflight_watermark": health.inflight_watermark(rows),
        "wall_s": round(dt, 2),
        "rounds_per_sec": round(rounds / dt, 2) if dt > 0 else None,
        "postmortem": None,
    }
    if not ok:
        # flight-recorder postmortem: the last window's wire trace in
        # the verify.trace dump format (replayable through the model
        # checker / drop-schedule machinery) + the health tail
        os.makedirs(postmortem_dir, exist_ok=True)
        base = os.path.join(postmortem_dir,
                            f"chaos_postmortem_{mix}_s{seed}_n{n}")
        trace_path = base + ".trace"
        trace_mod.write_trace(trace_path, last_window["entries"] or [])
        with open(base + ".health.jsonl", "w") as f:
            for r in rows[-2 * window:]:
                f.write(json.dumps(r) + "\n")
        row["postmortem"] = trace_path
    return row


def run_workload_cell(*, n: int, rounds: int, seed: int, window: int,
                      heal_margin: int, rate_milli: int = 1000,
                      stream=None, out: dict = None) -> dict:
    """The ISSUE-8 workload arm: a partition_heal cell with app-level
    RPC traffic riding the overlay, asserting the latency plane RECOVERS
    after the heal — the post-heal window's p99 (folded from the in-scan
    histogram deltas) must come back inside the SLO deadline while the
    sheds/retries/dead-letters that got the fabric through the partition
    stay counted in the row."""
    from partisan_tpu.models.stack import Lifted, Stacked
    from partisan_tpu.workload import arrivals, latency
    from partisan_tpu.workload.driver import WorkloadRpc

    sched = _mix_partition_heal(n, rounds)
    heal_rnd = sched.last_heal_round()
    cfg = pt.Config(n_nodes=n, inbox_cap=16, shuffle_interval=5,
                    seed=seed,
                    retransmit_interval=4, retransmit_backoff_factor=2,
                    retransmit_max_attempts=3, slo_deadline_rounds=16)
    drv = WorkloadRpc(cfg, promise_cap=16,
                      spec=arrivals.ArrivalSpec(
                          kind=arrivals.POISSON, max_issue=4),
                      rate_milli=rate_milli)
    proto = Stacked(HyParView(cfg), Lifted(drv))
    world = ps.cluster(pt.init_world(cfg, proto), proto,
                       [(i, (i - 1) // 2) for i in range(1, n)])
    registry = health.workload_registry()
    sink = _Rows()
    t0 = time.perf_counter()
    world, _ = telemetry.run_with_telemetry(
        cfg, proto, rounds, window=window, registry=registry,
        sinks=[sink], world=world, stream=stream,
        step_kw={"chaos": sched})
    dt = time.perf_counter() - t0
    if out is not None:
        out["world"], out["cfg"] = world, cfg

    rows = [r for r in sink.rows if "health_reach_frac" in r]
    conv = health.converged_round(rows, after=heal_rnd)
    converged = conv is not None and (conv - heal_rnd) <= heal_margin
    # latency folds from the cumulative in-scan histogram: the partition
    # window (fault start -> heal) vs the recovery window (the tail
    # after the overlay had heal_margin rounds to re-knit)
    recov_start = min(heal_rnd + heal_margin, rounds - 2)
    hist_part = latency.window_delta(rows, "rpc_latency",
                                     start_round=rounds // 4) \
        - latency.window_delta(rows, "rpc_latency", start_round=heal_rnd)
    hist_recov = latency.window_delta(rows, "rpc_latency",
                                      start_round=recov_start)
    p99_recov = latency.quantile_bound(hist_recov, 0.99)
    recovered = (hist_recov.sum() > 0
                 and p99_recov <= cfg.slo_deadline_rounds)
    last = rows[-1] if rows else {}
    row = {
        "bench": "chaos_soak_workload",
        "mix": "partition_heal",
        "seed": seed, "n_nodes": n, "rounds": rounds,
        "rate_milli": rate_milli,
        "heal_round": heal_rnd, "converged_round": conv,
        "heal_margin": heal_margin, "converged": bool(converged),
        "slo_deadline_rounds": cfg.slo_deadline_rounds,
        "completions_partition": int(hist_part.sum()),
        "p99_partition": latency.quantile_bound(
            np.maximum(hist_part, 0), 0.99),
        "completions_recovery": int(hist_recov.sum()),
        "p99_recovery": p99_recov,
        "p99_recovered": bool(recovered),
        "wl_issued": last.get("wl_issued"),
        "wl_shed": last.get("wl_shed"),
        "wl_retries": last.get("wl_retries"),
        "wl_dead_lettered": last.get("wl_dead_lettered"),
        "rpc_call_dropped": last.get("rpc_call_dropped"),
        "rpc_slo_ok": last.get("rpc_slo_ok"),
        "rpc_slo_violated": last.get("rpc_slo_violated"),
        "wall_s": round(dt, 2),
        "rounds_per_sec": round(rounds / dt, 2) if dt > 0 else None,
    }
    return row


def _append_bench_rows(rows, smoke: bool = False) -> None:
    """Unified bench ledger (ISSUE 18): mirror each cell as a canonical
    BenchRow (suite ``chaos_soak``, arm = fault mix).  The legacy
    BENCH_chaos.jsonl rows above are untouched.  Smoke runs land in
    /tmp so CI never dirties the committed trajectory (same policy as
    control_suite/load_suite)."""
    from partisan_tpu.telemetry import benchplane
    ledger_path = os.environ.get("PARTISAN_BENCH_LEDGER") or (
        "/tmp/BENCH_ledger_smoke.jsonl" if smoke else None)
    calib = benchplane.calibrate()
    benchplane.append_rows_nonfatal(
        [benchplane.make_row(
            "chaos_soak", r.get("mix", "unknown"),
            config={"seed": r.get("seed"),
                    "heal_margin": r.get("heal_margin")},
            n_nodes=r.get("n_nodes"), rounds=r.get("rounds"),
            rounds_per_sec=r.get("rounds_per_sec"),
            wall_s=r.get("wall_s"), calibration=calib,
            metrics={k: r[k] for k in ("converged", "heal_round",
                                       "converged_round",
                                       "chaos_dropped",
                                       "chaos_equivocated",
                                       "chaos_forged",
                                       "chaos_replayed",
                                       "chaos_corrupted",
                                       "wan_rtt_rounds",
                                       "p99_recovery") if k in r})
         for r in rows],
        ledger_path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--rounds", type=int, default=160)
    ap.add_argument("--window", type=int, default=32)
    ap.add_argument("--seeds", default="1,2,3,4")
    ap.add_argument("--mixes", default=None,
                    help="comma list of fault mixes (default: all; "
                         "--smoke defaults to lossy_combo but respects "
                         "an explicit --mixes)")
    ap.add_argument("--heal-margin", type=int, default=60)
    ap.add_argument("--out", default="BENCH_chaos.jsonl")
    ap.add_argument("--flight-cap", type=int, default=2048)
    ap.add_argument("--postmortem-dir", default="/tmp")
    ap.add_argument("--smoke", action="store_true",
                    help="one tiny cell (n=64, 1 seed, lossy_combo) — "
                         "the tier-1 smoke configuration")
    ap.add_argument("--checkpoint", metavar="DIR", default=None,
                    help="save campaign state here after every cell "
                         "(partisan_tpu.checkpoint directory)")
    ap.add_argument("--resume", action="store_true",
                    help="restore the --checkpoint ledger and continue "
                         "from the first unfinished cell")
    ap.add_argument("--workload", action="store_true",
                    help="run the ISSUE-8 workload arm instead of the "
                         "membership campaign: partition_heal cells "
                         "with compiled RPC traffic, asserting p99 "
                         "recovery after the heal")
    ap.add_argument("--rate-milli", type=int, default=1000,
                    help="workload arm offered load "
                         "(milli-requests/round/node)")
    ap.add_argument("--stream", action="store_true",
                    help="drain every round's metric row to the host "
                         "MID-SCAN (ordered io_callback) and print a "
                         "per-window heartbeat — live progress for "
                         "long soaks, at the cost of an uncacheable "
                         "program (recompiles each run)")
    ap.add_argument("--replay", metavar="FILE", default=None,
                    help="re-execute a chaos counterexample JSON "
                         "(verify.explorer / scripts/chaos_explore.py) "
                         "with a flight-recorder postmortem; exits 0 "
                         "iff the violation reproduces")
    # test hook: simulate a mid-campaign kill after N cells (exit 3,
    # BENCH not written — the checkpoint is the only survivor)
    ap.add_argument("--fail-after", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.replay:
        from partisan_tpu.verify import explorer
        res = explorer.replay_counterexample(
            args.replay, postmortem_dir=args.postmortem_dir)
        verdict = ("REPRODUCED" if res["reproduced"]
                   else "NOT REPRODUCED")
        print(f"{verdict} {res['invariant']} @ round "
              f"{res['first_violation_round']} "
              f"(expected {res['expected_round']})"
              + (f", postmortem={res['postmortem']}"
                 if res["postmortem"] else ""))
        return 0 if res["reproduced"] else 1

    if args.resume and not args.checkpoint:
        ap.error("--resume requires --checkpoint")

    if args.smoke:
        args.n, args.rounds, args.window = 64, 60, 20
        args.seeds = "1"
        if args.mixes is None:  # an explicit --mixes picks the smoke cell
            args.mixes = "lossy_combo"
        args.heal_margin = 25

    seeds = [int(s) for s in args.seeds.split(",") if s]
    mixes = [m for m in (args.mixes or ",".join(MIXES)).split(",") if m]
    for m in mixes:
        if m not in MIXES:
            ap.error(f"unknown mix {m!r}; have {sorted(MIXES)}")

    if args.workload:
        rows = []
        for seed in seeds:
            row = run_workload_cell(n=args.n, rounds=args.rounds,
                                    seed=seed, window=args.window,
                                    heal_margin=args.heal_margin,
                                    rate_milli=args.rate_milli,
                                    stream=(live_stream(args.window)
                                            if args.stream else None))
            rows.append(row)
            ok = row["converged"] and row["p99_recovered"]
            print(f"{'PASS' if ok else 'FAIL'} workload seed={seed}: "
                  f"heal@{row['heal_round']} "
                  f"converged@{row['converged_round']} "
                  f"p99_recovery={row['p99_recovery']} "
                  f"(partition p99={row['p99_partition']}, "
                  f"shed={row['wl_shed']}, retries={row['wl_retries']}, "
                  f"dead_lettered={row['wl_dead_lettered']}, "
                  f"{row['rounds_per_sec']} r/s)")
        failures = sum(1 for r in rows
                       if not (r["converged"] and r["p99_recovered"]))
        with open(args.out, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        _append_bench_rows(rows, smoke=args.smoke)
        print(f"\n{len(rows)} workload cells -> {args.out}; "
              f"{failures} failed p99-recovery-after-heal")
        return 1 if failures else 0

    rows = []
    completed = []  # [mix, seed] pairs, campaign order
    if args.resume:
        # ledger + integrity gate: the saved world must restore cleanly
        # against its own recorded config/protocol before we trust the
        # completed-cell list (the shard-aware load validates every
        # leaf's shape and dtype)
        extra = checkpoint.load_extra(args.checkpoint)
        completed = [list(c) for c in extra.get("completed", [])]
        rows = list(extra.get("rows", []))
        ccfg = checkpoint.load_config(args.checkpoint)
        checkpoint.load(args.checkpoint,
                        pt.init_world(ccfg, HyParView(ccfg)),
                        cfg=ccfg, proto="HyParView")
        print(f"resumed {args.checkpoint}: {len(completed)} cells "
              f"already complete")

    done_this_run = 0
    for mix in mixes:
        for seed in seeds:
            if [mix, seed] in completed:
                continue
            cell_out = {}
            row = run_cell(n=args.n, rounds=args.rounds, seed=seed,
                           mix=mix, window=args.window,
                           heal_margin=args.heal_margin,
                           flight_cap=args.flight_cap,
                           postmortem_dir=args.postmortem_dir,
                           stream=(live_stream(args.window)
                                   if args.stream else None),
                           out=cell_out)
            rows.append(row)
            completed.append([mix, seed])
            verdict = "PASS" if row["converged"] else "FAIL"
            print(f"{verdict} {mix} seed={seed}: heal@{row['heal_round']}"
                  f" converged@{row['converged_round']}"
                  f" ({row['rounds_per_sec']} r/s,"
                  f" dropped={row['chaos_dropped']},"
                  f" watermark={row['inflight_watermark']:.0f}"
                  + (f", postmortem={row['postmortem']}"
                     if row["postmortem"] else "") + ")")
            if args.checkpoint:
                checkpoint.save(args.checkpoint, cell_out["cfg"],
                                cell_out["world"],
                                extra={"completed": completed,
                                       "rows": rows},
                                proto="HyParView")
            done_this_run += 1
            if args.fail_after and done_this_run >= args.fail_after:
                print("injected kill: exiting mid-campaign",
                      file=sys.stderr)
                return 3

    failures = sum(1 for r in rows if not r["converged"])
    with open(args.out, "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    _append_bench_rows(rows, smoke=args.smoke)
    print(f"\n{len(rows)} cells -> {args.out}; {failures} failed "
          f"convergence-after-heal")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
