"""Phase + primitive ablation for the dense-SCAMP round at N=2^16
(ROADMAP 1d residual: ~4.9 rounds/s — where do the ~200 ms go?).

Usage: python scripts/profile_scamp.py [--n 65536] [--rounds 100]
"""
from __future__ import annotations

import argparse
import functools
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import partisan_tpu as pt  # noqa: E402
from partisan_tpu.models import scamp_dense as sd  # noqa: E402
from partisan_tpu.models.hyparview_dense import reverse_select  # noqa: E402


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def run_skip(st, n_rounds, cfg, churn, skip):
    step = sd.make_dense_scamp_round(cfg, churn, skip=skip)
    out, _ = jax.lax.scan(lambda s, _: (step(s), None), st, None,
                          length=n_rounds)
    return out


def timed(tag, fn, warm_arg, iters=1):
    out = fn(warm_arg)
    jax.tree_util.tree_map(
        lambda x: float(jnp.sum(x.astype(jnp.float32))), out)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(warm_arg)
        jax.tree_util.tree_map(
            lambda x: float(jnp.sum(x.astype(jnp.float32))), out)
        ts.append((time.perf_counter() - t0) / iters)
    print(f"{tag:30s} {statistics.median(ts)*1e3:9.2f} ms")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 16)
    ap.add_argument("--rounds", type=int, default=100)
    args = ap.parse_args()
    cfg = pt.Config(n_nodes=args.n)
    n, rounds = args.n, args.rounds
    st = sd.dense_scamp_init(cfg)
    st.partial.block_until_ready()

    for tag, churn, skip in (
            ("full", 0.01, ()),
            ("no_churn", 0.0, ()),
            ("skip_admit", 0.01, ("admit",)),
            ("skip_inview", 0.01, ("inview",)),
            ("skip_admit+inview", 0.01, ("admit", "inview"))):
        def f(s, churn=churn, skip=skip):
            return run_skip(s, rounds, cfg, churn, tuple(skip))
        timed(tag, f, st, iters=rounds)

    # primitive probes at shape
    P, C = sd.walker_caps(cfg)
    M = n * C
    key = jax.random.PRNGKey(0)
    flat_pos = jax.random.randint(key, (M,), -1, n, jnp.int32)
    vec = jax.random.randint(jax.random.fold_in(key, 1), (n,), 0, 99)
    partial = jax.random.randint(jax.random.fold_in(key, 2), (n, P), -1,
                                 n, jnp.int32)

    @jax.jit
    def probe_scalar_gather(fp):
        def body(s, i):
            return s + vec[jnp.clip(fp + i, 0, n - 1)], None
        out, _ = jax.lax.scan(body, jnp.zeros((M,), jnp.int32),
                              jnp.arange(50))
        return out
    timed("vec[1M idx] scalar gather", probe_scalar_gather, flat_pos,
          iters=50)

    @jax.jit
    def probe_flat_hop(fp):
        flat = partial.reshape(-1)
        def body(s, i):
            return s + flat[jnp.clip(fp + i, 0, n - 1) * P
                            + (i % P)], None
        out, _ = jax.lax.scan(body, jnp.zeros((M,), jnp.int32),
                              jnp.arange(50))
        return out
    timed("hop gather [1M from N*P]", probe_flat_hop, flat_pos, iters=50)

    @jax.jit
    def probe_rs(fp):
        def body(s, i):
            ch = reverse_select(jnp.where((fp + i) % 3 == 0, fp, -1),
                                i.astype(jnp.uint32), n, 4)
            return s + ch[:, 0], None
        out, _ = jax.lax.scan(body, jnp.zeros((n,), jnp.int32),
                              jnp.arange(20))
        return out
    timed("reverse_select M=1M c=4", probe_rs, flat_pos, iters=20)

    @jax.jit
    def probe_reset_mask(pv):
        reset = vec < 5
        def body(s, i):
            out = jnp.where(reset[jnp.clip(s, 0, n - 1)] & (s >= 0), -1,
                            s + 0 * i)
            return out, None
        out, _ = jax.lax.scan(body, pv, jnp.arange(50))
        return out
    timed("reset[clip(partial)] [N,P]", probe_reset_mask, partial,
          iters=50)


if __name__ == "__main__":
    main()
