"""Performance sweep — the analog of the reference's perf harness
(``make perf`` + ``bin/perf-suite.sh`` sweeping size/concurrency/RTT into
``results.csv``, test/partisan_SUITE.erl:1029-1136).

Sweeps the BASELINE configs (BASELINE.md) on whatever device JAX offers,
timing whole-run-on-device loops (engine.make_run_scan — zero host
round-trips), and appends one CSV row per config:

    config,n_nodes,rounds,seconds,rounds_per_sec,health

Usage:  python scripts/perf_suite.py [--out results.csv] [--quick]
"""

from __future__ import annotations

import argparse
import csv
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import partisan_tpu as pt  # noqa: E402
from partisan_tpu import peer_service  # noqa: E402
from partisan_tpu.engine import make_run_scan, init_world  # noqa: E402
from partisan_tpu.models.demers import rumor_init, rumor_run  # noqa: E402
from partisan_tpu.models.full_membership import FullMembership  # noqa: E402
from partisan_tpu.models.hyparview import HyParView  # noqa: E402
from partisan_tpu.models.plumtree import Plumtree  # noqa: E402
from partisan_tpu.models.scamp import ScampV2  # noqa: E402
from partisan_tpu.models.stack import Stacked  # noqa: E402
from partisan_tpu.ops import graph  # noqa: E402


def time_engine(name, cfg, proto, rounds, health_fn, rows, out_cap=None):
    world = init_world(cfg, proto, out_cap=out_cap)
    world = peer_service.cluster(
        world, proto, [(i, 0) for i in range(1, cfg.n_nodes)], stagger=8)
    run = make_run_scan(cfg, proto, rounds, out_cap=out_cap)
    w2, _ = run(world)           # compile + warm
    int(w2.rnd)                  # scalar readback = real sync (bench.py notes)
    world2 = init_world(cfg, proto, out_cap=out_cap)  # distinct input
    world2 = peer_service.cluster(
        world2, proto, [(i, 1 % cfg.n_nodes) for i in range(2, cfg.n_nodes)],
        stagger=8)
    t0 = time.perf_counter()
    w3, _ = run(world2)
    int(w3.rnd)                  # readback inside the timed region
    dt = time.perf_counter() - t0
    health = health_fn(w2)
    rows.append([name, cfg.n_nodes, rounds, round(dt, 4),
                 round(rounds / dt, 1), health])
    print(f"{name:28s} N={cfg.n_nodes:<7d} {rounds/dt:9.1f} rounds/s  "
          f"({health})")


class _RowSink(list):
    """Row collector that FLUSHES each row to the CSV as it lands —
    a crashed group (e.g. a TPU OOM mid-sweep) no longer discards the
    rows every earlier group already measured."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path

    def append(self, row) -> None:
        super().append(row)
        new = not os.path.exists(self._path)
        with open(self._path, "a", newline="") as f:
            w = csv.writer(f)
            if new:
                w.writerow(["config", "n_nodes", "rounds", "seconds",
                            "rounds_per_sec", "health"])
            w.writerow(row)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="results.csv")
    ap.add_argument("--quick", action="store_true",
                    help="small round counts (CI smoke)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the image's TPU plugin "
                         "ignores JAX_PLATFORMS)")
    ap.add_argument("--only", default=None,
                    help="run configs by name substring "
                         "(comma list = any-of)")
    ap.add_argument("--gather", type=int, default=None,
                    help="deliver_gather_cap for the engine configs "
                         "(sparse dispatch; see Config)")
    ap.add_argument("--node-cap", type=int, default=None,
                    help="node_emit_cap: per-node emission pre-compaction "
                         "budget (see Config)")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    R = 50 if args.quick else 200
    rows = _RowSink(args.out)
    want = lambda name: args.only is None or any(
        tok and tok in name for tok in args.only.split(","))

    if want("full_membership"):
        # BASELINE #1: full membership, small cluster
        cfg = pt.Config(n_nodes=16, inbox_cap=32, periodic_interval=2,
                        deliver_gather_cap=args.gather,
                        node_emit_cap=args.node_cap)
        time_engine("full_membership", cfg, FullMembership(cfg), R,
                    lambda w: "converged" if bool(
                        (np.asarray(jax.vmap(FullMembership(cfg).member_mask)(
                            w.state)).all())) else "partial", rows)

    if want("hyparview"):
        # BASELINE #2: HyParView N=64
        cfg = pt.Config(n_nodes=64, inbox_cap=8, shuffle_interval=5,
                        deliver_gather_cap=args.gather,
                        node_emit_cap=args.node_cap)
        hv = HyParView(cfg)
        time_engine("hyparview", cfg, hv, R,
                    lambda w: "connected" if bool(graph.is_connected(
                        graph.adjacency_from_views(w.state.active, 64)))
                    else "DISCONNECTED", rows)

    if want("plumtree"):
        # BASELINE #3: plumtree over hyparview N=64
        cfg = pt.Config(n_nodes=64, inbox_cap=12, shuffle_interval=5,
                        deliver_gather_cap=args.gather,
                        node_emit_cap=args.node_cap)
        time_engine("plumtree_over_hyparview", cfg,
                    Stacked(HyParView(cfg), Plumtree(cfg, n_keys=1)), R,
                    lambda w: "ok", rows)

    if want("scamp"):
        # BASELINE #4: SCAMP v2 at 1024.  Subscription walks need time to
        # knit the overlay at this N (measured: 50 rounds DISCONNECTED,
        # 150 rounds connected), so quick mode floors the round count —
        # scamp is deliberately slower than the other quick configs so
        # its health line stays meaningful.
        # engine knobs default to the measured optimum (running-offset
        # collect + chunked gather delivery + occupied-prefix slot loop
        # + tight carry, ROADMAP #1: 2.0 -> ~53 rounds/s on true CPU;
        # connectivity preserved — SCAMP's subscription redundancy
        # absorbs the counted join-storm drops)
        # 0 disables a knob explicitly; None means "use the tuned default"
        gather = 8 if args.gather is None else (args.gather or None)
        node_cap = 8 if args.node_cap is None else (args.node_cap or None)
        cfg = pt.Config(n_nodes=1024, inbox_cap=6, periodic_interval=5,
                        deliver_gather_cap=gather, node_emit_cap=node_cap)
        sc = ScampV2(cfg)
        scamp_health = lambda w: "connected" if bool(graph.is_connected(
            graph.adjacency_from_views(w.state.partial, 1024))) \
            else "DISCONNECTED"
        time_engine("scamp_v2", cfg, sc, max(R, 150), scamp_health, rows,
                    out_cap=4 * 1024)
        # the ROUND-1 workload parameters under the same engine, so the
        # cross-round engine-speedup comparison is apples-to-apples (the
        # tuned row above also changes inbox_cap/out_cap — a workload
        # redefinition, not only an engine change)
        cfg1 = pt.Config(n_nodes=1024, inbox_cap=16, periodic_interval=5)
        time_engine("scamp_v2_r1cfg", cfg1, ScampV2(cfg1), max(R, 150),
                    scamp_health, rows)

    if want("hv_dense") and jax.devices()[0].platform == "tpu":
        # VERDICT r3 #1 + r4 #2: the dense-representation HyParView
        # re-layout, now phase-staggered (run_dense_staggered) at the
        # REFERENCE cadence — shuffle 10 / promotion 5 / delivery 1
        # (partisan_hyparview_peer_service_manager.erl:27-28, the
        # Config defaults).  Every k=5th round is a heavy maintenance
        # round batching the widened due window; rounds between carry
        # churn + isolation reseed.  1%/round churn keeps the fault
        # plane hot; health asserts the overlay heals once churn stops.
        import statistics as _st
        from partisan_tpu.models.hyparview_dense import (
            connectivity, dense_init, run_dense, run_dense_chunked,
            run_dense_staggered, run_dense_staggered_chunked)
        def hv_bench(name, n, total_rounds, cfg, run_trial, cadence):
            """Shared hv_dense timing discipline (one copy for the
            flat continuity row AND the staggered sweep): warmup
            compile + sync, 3 trials on reseeded worlds with a scalar
            readback in the timed region, churn-free flat-round heal
            (repair every round — connectivity must restore once churn
            stops), connectivity health row."""
            warm = run_trial(dense_init(cfg))
            float(jnp.sum(warm.active))          # compile + real sync
            # same memory discipline as the scamp block: at 2^22 the
            # overlay planes + staggered sort temporaries OOM with a
            # third state live
            del warm
            rates, out = [], None
            for t in range(3):
                w0 = dense_init(cfg.replace(seed=11 + 13 * t))
                out = None                       # free previous trial
                t0 = time.perf_counter()
                out = run_trial(w0)
                float(jnp.sum(out.active))                    # sync
                rates.append(total_rounds / (time.perf_counter() - t0))
                del w0
            # heal window: 60 churn-free every-round-repair rounds —
            # the staggered cadence accrues more un-repaired damage
            # than the flat program did, and 20 rounds left a
            # 10^-4-fraction of 2^16/2^20 nodes still re-attaching
            # (chunked: a 60-round flat launch faults at 2^22)
            out = run_dense_chunked(out, 60, cfg)
            h = {kk: float(np.asarray(v)) for kk, v in
                 connectivity(out).items()}
            rps = _st.median(rates)
            health = ("connected" if h.get("connected") else
                      f"reached={h.get('reached'):.0f}/"
                      f"{h.get('live'):.0f}")
            rows.append([name, n, total_rounds,
                         round(total_rounds / rps, 4), round(rps, 1),
                         f"{health},"
                         f"mean_active={h.get('mean_active'):.1f},"
                         f"cadence={cadence},churn=0.01"])
            print(f"{name:28s} N={n:<7d} {rps:9.1f} rounds/s"
                  f"  ({health})")

        # continuity row: round-4's every-round program at its hotter
        # 4/2 cadence, so the cross-round speedup decomposition stays
        # honest (program improvements vs cadence change)
        n, rnds = 1 << 12, (200 if args.quick else 2000)
        fcfg = pt.Config(n_nodes=n, shuffle_interval=4,
                         random_promotion_interval=2)
        hv_bench("hv_dense_flat_4096", n, rnds, fcfg,
                 lambda w: run_dense(w, rnds, fcfg, 0.01), "flat4/2")
        # official rows: staggered, reference cadence.  2^21/2^22
        # (round 5): the same program in launch_cap_for-bounded
        # launches — 2M and 4M simulated nodes on ONE chip
        sweep = [(1 << 12, 2000), (1 << 16, 500), (1 << 20, 200),
                 (1 << 21, 100), (1 << 22, 100)]
        k = 5
        for n, rnds in sweep:
            if args.quick:
                rnds = min(rnds, 200)
            blocks = rnds // (2 * k)          # one block = 2k rounds
            total = blocks * 2 * k
            cfg = pt.Config(n_nodes=n)
            hv_bench(
                f"hv_dense_{n}", n, total, cfg,
                lambda w, blocks=blocks, cfg=cfg:
                    run_dense_staggered_chunked(w, blocks, cfg, 0.01, k),
                f"ref10/5k{k}")

    if want("scamp_dense") and jax.devices()[0].platform == "tpu":
        # round 3: the second membership strategy re-laid TPU-fast —
        # SCAMP subscription walks as whole-array ops (scamp_dense.py)
        # with 1%/round restart churn; health = weak connectivity +
        # mean view size after a settle window
        import statistics as _st
        from partisan_tpu.models.scamp_dense import (
            dense_scamp_init, run_dense_scamp,
            run_dense_scamp_staggered_chunked, scamp_health)

        def scamp_bench(name, n, rnds, cfg, run_trial, cadence):
            """Shared scamp_dense timing discipline (flat + staggered
            rows): warmup compile+sync, 3 reseeded trials, settle, weak
            connectivity health."""
            warm = run_trial(dense_scamp_init(cfg))
            float(jnp.sum(warm.partial))         # compile + real sync
            # the 2^20 state is ~2.8 GB (P=166 view cap x 4 int32
            # planes); holding warm + the previous trial's out + the
            # in-flight trial OOMs the chip — keep at most two states
            # live (the in-flight trial's input and output)
            del warm
            rates, out = [], None
            for t in range(3):
                s0 = dense_scamp_init(cfg.replace(seed=17 + 5 * t))
                out = None                       # free previous trial
                t0 = time.perf_counter()
                out = run_trial(s0)
                float(jnp.sum(out.partial))      # sync
                rates.append(rnds / (time.perf_counter() - t0))
                del s0
            out = run_dense_scamp(out, 60, cfg)  # settle, then health
            h = {k: float(np.asarray(v))
                 for k, v in scamp_health(out).items()}
            rps = _st.median(rates)
            health = ("connected" if h.get("connected")
                      else f"reached={h['reached']:.0f}/{h['live']:.0f}")
            rows.append([name, n, rnds,
                         round(rnds / rps, 4), round(rps, 1),
                         f"{health},mean_view={h['mean_view']:.1f},"
                         f"{cadence}churn=0.01"])
            print(f"{name:28s} N={n:<7d} "
                  f"{rps:9.1f} rounds/s  ({health})")

        # N>=2^16 runs chunked (scamp_dense.launch_cap_for): single
        # launches beyond ~100 scanned rounds at 2^16 — and beyond ~50
        # at 2^20 — fault the TPU worker
        # (scripts/repro_scamp_dense_fault.py pins it, ROADMAP 1d);
        # the capped launches soak clean (1000+ rounds at both shapes)
        for n, rnds in ((1 << 12, 2000), (1 << 16, 200), (1 << 20, 200)):
            if args.quick:
                rnds = min(rnds, 200)
            cfg = pt.Config(n_nodes=n)
            scamp_bench(
                f"scamp_dense_{n}", n, rnds, cfg,
                lambda s0, cfg=cfg, rnds=rnds:
                    run_dense_scamp(s0, rnds, cfg, 0.01), "")
            # ISSUE 2: the reference-cadence staggered rows (walk
            # delivery every round, resub + sweep every k=5th —
            # scamp_v2's periodic/1 at 10 s vs 1 s delivery); the
            # k=1-reduction and chunk-equivalence tests pin semantics
            k = 5
            blocks = rnds // k
            scamp_bench(
                f"scamp_dense_stag_{n}", n, blocks * k, cfg,
                lambda s0, cfg=cfg, blocks=blocks:
                    run_dense_scamp_staggered_chunked(
                        s0, blocks, cfg, 0.01, k),
                f"cadence=ref10/1k{k},")

    if want("pt_dense") and jax.devices()[0].platform == "tpu":
        # VERDICT r2 weak #6: broadcast layer at TPU scale — plumtree
        # over the DENSE HyParView (fused membership+broadcast scan)
        # with 1%/round churn, plus a single-shot coverage-depth row.
        import statistics as _st
        from partisan_tpu.models.hyparview_dense import (
            connectivity, dense_init, run_dense)
        from partisan_tpu.models.plumtree_dense import (
            coverage_rounds, pt_dense_init, run_pt_dense)
        n, rnds = 1 << 12, 200 if args.quick else 2000
        cfg = pt.Config(n_nodes=n, shuffle_interval=4,
                        random_promotion_interval=2)
        # coverage depth needs a CONNECTED static overlay.  A churn-FREE
        # warmup can leave a saturated 2-node island (every active view
        # full => all neighbor proposals declined — an absorbing state
        # the reference shares); churn keeps rooms opening, so warm WITH
        # churn, settle briefly without, and retry until connected.
        hv0 = run_dense(dense_init(cfg), 300, cfg, 0.01)
        hv0 = run_dense(hv0, 50, cfg)
        cov_ok = bool(np.asarray(connectivity(hv0)["connected"]))
        for _ in range(3):
            if cov_ok:
                break
            hv0 = run_dense(hv0, 100, cfg, 0.01)
            hv0 = run_dense(hv0, 50, cfg)
            cov_ok = bool(np.asarray(connectivity(hv0)["connected"]))
        # never abort the whole sweep here — rows collected so far are
        # only written at the end of main(); skip just the coverage row
        if not cov_ok:
            print("WARN: static overlay failed to connect; "
                  "skipping the coverage row")
        def pt_bench(n_, cfg_, hv0_, cov_ok_, warm_trial, run_bcast,
                     rnds_, cadence):
            """Shared pt_dense timing discipline: warmup compile+sync,
            3 trials on reseeded overlays with a scalar readback in the
            timed region, root-tracking health, optional coverage row."""
            hv1, p1 = run_bcast(hv0_, pt_dense_init(cfg_))
            float(jnp.sum(p1.seq))           # compile + real sync
            rates = []
            for t in range(3):
                hvt = warm_trial(t)
                t0 = time.perf_counter()
                hv2, p2 = run_bcast(hvt, pt_dense_init(cfg_))
                root_seq = float(np.asarray(p2.seq[0]))      # sync
                rates.append(rnds_ / (time.perf_counter() - t0))
            lag_ok = float(np.mean(
                (np.asarray(p2.seq[0]) - np.asarray(p2.seq)) <= 5))
            rps = _st.median(rates)
            rows.append([f"pt_dense_{n_}", n_, rnds_,
                         round(rnds_ / rps, 4), round(rps, 1),
                         f"root_seq={root_seq:.0f},"
                         f"track<=5={lag_ok:.2f},{cadence}churn=0.01"])
            print(f"{'pt_dense_' + str(n_):28s} N={n_:<7d} "
                  f"{rps:9.1f} rounds/s  (track={lag_ok:.2f})")
            # measure coverage regardless and report the honest
            # fraction: at 2^16+/1M a 10^-4 sliver of the overlay can
            # still be re-attaching after the heal window (absorbing
            # saturated islands, an equilibrium the reference shares),
            # and skipping the row entirely hid the broadcast-depth
            # number the row exists to record
            if not cov_ok_:
                print(f"WARN: N={n_} overlay not fully connected; "
                      f"coverage fraction below reflects it")
            cov_r, cov = coverage_rounds(hv0_, cfg_, max_rounds=64)
            # .6f: at 2^16+ a 2-node absorbing island reads 0.99997 —
            # 4 decimals rounded that up to a false "1.0000"
            rows.append([f"pt_dense_cov_{n_}", n_, cov_r, 0, 0,
                         f"coverage={cov:.6f},"
                         f"rounds_to_full={cov_r}"])
            print(f"{'pt_dense_cov_' + str(n_):28s} N={n_:<7d} "
                  f"coverage {cov:.6f} in {cov_r} rounds")

        pt_bench(
            n, cfg, hv0, cov_ok,
            lambda t: run_dense(dense_init(cfg.replace(seed=23 + 7 * t)),
                                300, cfg),
            lambda hv_, pt0: run_pt_dense(hv_, pt0, rnds, cfg, 0.01),
            rnds, "")

        # VERDICT r4 #3: broadcast at 2^16 (ungated there) — fused
        # membership+broadcast on the phase-staggered cadence
        # (run_pt_dense_staggered: plumtree ticks every round, the
        # reference's 1 s lazy tick, over the 10/5 maintenance timers)
        # with 1%/round churn, root-tracking health + a coverage row.
        from partisan_tpu.models.hyparview_dense import (
            run_dense_staggered)
        from partisan_tpu.models.plumtree_dense import (
            run_pt_dense_staggered)
        n16 = 1 << 16
        k = 5
        blocks16 = (200 if args.quick else 500) // (2 * k)
        rnds16 = blocks16 * 2 * k
        cfg16 = pt.Config(n_nodes=n16)
        hv0 = run_dense_staggered(dense_init(cfg16), 30, cfg16, 0.01, k)
        hv0 = run_dense(hv0, 60, cfg16)          # heal for coverage
        cov_ok16 = bool(np.asarray(connectivity(hv0)["connected"]))
        for _ in range(3):
            if cov_ok16:
                break
            hv0 = run_dense(hv0, 60, cfg16)      # more heal, no damage
            cov_ok16 = bool(np.asarray(connectivity(hv0)["connected"]))
        pt_bench(
            n16, cfg16, hv0, cov_ok16,
            lambda t: run_dense_staggered(
                dense_init(cfg16.replace(seed=23 + 7 * t)), 30, cfg16,
                0.01, k),
            lambda hv_, pt0: run_pt_dense_staggered(
                hv_, pt0, blocks16, cfg16, 0.01, 0, k),
            rnds16, "cadence=ref10/5k5,")

        # round 5: broadcast at 2^20 and 2^21 — the fused program
        # runs clean in <=50-round launches at both shapes
        # (scripts/repro_pt_dense_fault.py), so the big-N rows ride
        # run_pt_dense_staggered_chunked (SCAMP cannot follow past
        # 2^20: its stamp/view planes hit a memory wall at 2^21)
        if not args.quick:
            from partisan_tpu.models.hyparview_dense import (
                run_dense_chunked, run_dense_staggered_chunked)
            from partisan_tpu.models.plumtree_dense import (
                run_pt_dense_staggered_chunked)
            for nbig in (1 << 20, 1 << 21):
                blocksb = 10                      # 100 rounds
                rndsb = blocksb * 2 * k
                cfgb = pt.Config(n_nodes=nbig)
                hv0 = run_dense_staggered_chunked(
                    dense_init(cfgb), 20, cfgb, 0.01, k)
                hv0 = run_dense_chunked(hv0, 60, cfgb)  # heal for cov
                cov_okb = bool(
                    np.asarray(connectivity(hv0)["connected"]))
                for _ in range(2):
                    if cov_okb:
                        break
                    hv0 = run_dense_chunked(hv0, 60, cfgb)
                    cov_okb = bool(
                        np.asarray(connectivity(hv0)["connected"]))
                pt_bench(
                    nbig, cfgb, hv0, cov_okb,
                    lambda t, cfgb=cfgb: run_dense_staggered_chunked(
                        dense_init(cfgb.replace(seed=23 + 7 * t)), 20,
                        cfgb, 0.01, k),
                    lambda hv_, pt0, cfgb=cfgb, blocksb=blocksb:
                        run_pt_dense_staggered_chunked(
                            hv_, pt0, blocksb, cfgb, 0.01, 0, k),
                    rndsb, "cadence=ref10/5k5,")

    if want("echo"):
        # the reference's performance_test proper: SIZE x CONCURRENCY x RTT
        # echo streams between 2 nodes (partisan_SUITE.erl:1029-1136); one
        # row per swept point, value = completed echoes/sec
        from partisan_tpu.models.echo import Echo
        from partisan_tpu.peer_service import send_ctl
        sweep = [(1, 256, 0), (8, 256, 0), (8, 4096, 0), (8, 256, 3)] \
            if args.quick else \
            [(c, s, r) for c in (1, 4, 8) for s in (256, 4096) for r in (0, 3)]
        for conc, words, rtt in sweep:
            total = 100
            cfg = pt.Config(n_nodes=2, inbox_cap=2 * conc + 2)
            proto = Echo(cfg, concurrency=conc, size_words=words,
                         total=total, rtt=rtt)
            rounds = (total + 2) * 2 * (1 + rtt)
            run = make_run_scan(cfg, proto, rounds)
            w0 = send_ctl(init_world(cfg, proto), proto, 0, "ctl_start",
                          peer=0)
            w1, _ = run(w0)
            int(np.asarray(w1.state.sent[0]).sum())  # compile + real sync
            # distinct input bytes (peer is unused by the handler) so the
            # TPU tunnel's (executable, input) result cache can't replay
            # the warmup, and a scalar readback INSIDE the timed region —
            # block_until_ready alone can return early through the tunnel
            # (see bench.py measurement notes)
            w0 = send_ctl(init_world(cfg, proto), proto, 0, "ctl_start",
                          peer=1)
            t0 = time.perf_counter()
            w1, _ = run(w0)
            msgs = int(np.asarray(w1.state.sent[0]).sum())
            dt = time.perf_counter() - t0
            name = f"echo_c{conc}_w{words}_rtt{rtt}"
            # rate column stays rounds/sec like every other row; the
            # echoes/sec figure goes in the health column (unit differs)
            rows.append([name, 2, rounds, round(dt, 4),
                         round(rounds / dt, 1),
                         f"echoes={msgs},echoes_per_sec={msgs/dt:.1f}"])
            print(f"{name:28s} N=2       {msgs/dt:9.1f} echoes/s")

    if want("echo_mb"):
        # VERDICT r3 #6: the reference's FULL payload range — SIZE
        # {1,2,4,8} MB x RTT {1,20,100} ms (partisan_SUITE.erl:1029-1136
        # + bin/perf-suite.sh's tc-netem RTT axis).  Cadence mapping:
        # ONE ENGINE ROUND = 1 ms of transport latency, so an RTT of
        # k ms stamps delay=k rounds on each hop (the engine holds the
        # message exactly k rounds — SURVEY §4.2's '$delay' plane).
        # Payload bytes, not message count, dominate these rows: each
        # in-flight message carries MB-scale int32 words through the
        # router's sort-route-gather, which is the regime the 1-16 KB
        # sweep above never touches.  plain (p1) vs connection-lane
        # parallelism (p4, the reference's PARALLELISM axis) at the
        # sweep corners.
        from partisan_tpu.models.echo import Echo
        from partisan_tpu.peer_service import send_ctl
        mb_sweep = [(mb, rtt, 1) for mb in (1, 2, 4, 8)
                    for rtt in (1, 20, 100)]
        mb_sweep += [(mb, rtt, 4) for mb in (1, 8) for rtt in (1, 100)]
        if args.quick:
            mb_sweep = [(1, 1, 1), (8, 1, 1)]
        for mb, rtt, par in mb_sweep:
            words = mb * (1 << 20) // 4
            conc = 4
            total = {1: 16, 20: 12, 100: 8}[rtt]
            cfg = pt.Config(n_nodes=2, inbox_cap=2 * conc + 2,
                            parallelism=par)
            proto = Echo(cfg, concurrency=conc, size_words=words,
                         total=total, rtt=rtt)
            rounds = (total + 2) * 2 * (1 + rtt)
            run = make_run_scan(cfg, proto, rounds)
            w0 = send_ctl(init_world(cfg, proto), proto, 0, "ctl_start",
                          peer=0)
            w1, _ = run(w0)
            int(np.asarray(w1.state.sent[0]).sum())  # compile + sync
            w0 = send_ctl(init_world(cfg, proto), proto, 0, "ctl_start",
                          peer=1)
            t0 = time.perf_counter()
            w1, _ = run(w0)
            msgs = int(np.asarray(w1.state.sent[0]).sum())
            dt = time.perf_counter() - t0
            name = f"echo_mb{mb}_rtt{rtt}_p{par}"
            mbps = msgs * mb / dt          # one-way delivered payload
            rows.append([name, 2, rounds, round(dt, 4),
                         round(rounds / dt, 1),
                         f"echoes={msgs},mb_per_sec={mbps:.1f},"
                         f"size_mb={mb},rtt_ms={rtt}"])
            print(f"{name:28s} N=2       {mbps:9.1f} MB/s "
                  f"({msgs} echoes)")

    if want("rumor"):
        # BASELINE #5: rumor fast path at 1e6 (the bench.py headline).
        # The timed seed must be FRESH per invocation, not merely
        # different from the warmup: the tunnel's (executable, input)
        # result cache persists across processes, and a fixed timed
        # seed replayed a cached run as a bogus 600k-rounds/s row
        # (round 5; bench.py's notes describe the same trap).  Drawn
        # from [1, n) so it can NEVER equal the warmup seed 0 and
        # replay the in-process cache either (ADVICE r5)
        n, rounds = 1_000_000, 1000
        seed = 1 + int.from_bytes(os.urandom(4), "little") % (n - 1)
        out = rumor_run(rumor_init(n, 0), rounds, n, 2, 1, 0.01)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = rumor_run(rumor_init(n, seed), rounds, n, 2, 1, 0.01)
        float(out.infected.mean())       # scalar readback = real sync
        dt = time.perf_counter() - t0
        rows.append(["rumor_mongering_1e6", n, rounds, round(dt, 4),
                     round(rounds / dt, 1),
                     f"infected={float(out.infected.mean()):.2f}"])
        print(f"{'rumor_mongering_1e6':28s} N={n:<7d} "
              f"{rounds/dt:9.1f} rounds/s")

    def time_kernel(name, run_fn, n, rounds):
        """Shared fused-kernel timing discipline: warmup compile + sync,
        then median of 3 trials on distinct inputs (tunnel variance is
        up to 4x; see the measurement notes)."""
        import statistics
        from partisan_tpu.models.demers import rumor_pack
        out = run_fn(rumor_pack(rumor_init(n, 0)))
        float(jnp.mean(jnp.bitwise_count(out.infected)))  # sync
        rates, frac = [], 0.0
        # per-invocation salt: fixed trial seeds re-used across
        # processes can hit the tunnel's persistent result cache (the
        # rumor_mongering_1e6 row measured a replay as 600k rounds/s)
        salt = int.from_bytes(os.urandom(4), "little")
        for t in range(3):
            w0 = rumor_pack(rumor_init(n, (104729 * (t + 3) + salt) % n))
            t0 = time.perf_counter()
            out = run_fn(w0)
            frac = float(jnp.mean(jnp.bitwise_count(out.infected) / 32.0))
            rates.append(rounds / (time.perf_counter() - t0))
        rps = statistics.median(rates)
        rows.append([name, n, rounds, round(rounds / rps, 4),
                     round(rps, 1), f"infected={frac:.2f},device=tpu"])
        print(f"{name:28s} N={n:<7d} {rps:9.1f} rounds/s")

    if want("rumor_fused") and jax.devices()[0].platform == "tpu":
        # the bench.py headline kernel (VMEM-resident, N=2^20)
        from partisan_tpu.ops.rumor_kernel import rumor_run_fused
        n, rounds = 1 << 20, 20000
        time_kernel("rumor_fused_pallas",
                    lambda w: rumor_run_fused(w, rounds, n, 2, 1, 0.01),
                    n, rounds)

    if want("rumor_hbm") and jax.devices()[0].platform == "tpu":
        # ROADMAP #2: the HBM-resident blocked kernel past the VMEM limit
        # (2^22).  Roll-compute-bound: rounds/s scales ~1/N.
        from partisan_tpu.ops.rumor_kernel_hbm import rumor_run_hbm
        for logn, rnds in ((24, 3000), (26, 1000)):
            nn = 1 << logn
            time_kernel(
                f"rumor_hbm_2e{logn}",
                lambda w, nn=nn, rnds=rnds: rumor_run_hbm(
                    w, rnds, nn, 2, 1, 0.01, 1024, False, True),
                nn, rnds)

    print(f"appended {len(rows)} rows to {args.out} "
          f"(device={jax.devices()[0].platform})")


if __name__ == "__main__":
    main()
