"""Chunked-launch probe for the dense-plumtree TPU fault at N=2^20
(ROADMAP 1d family; the SCAMP sibling is repro_scamp_dense_fault.py).

History: the fused membership+broadcast scan (run_pt_dense) faulted
the v5e worker at N=2^20 in a SINGLE long scan (the bare dense-
HyParView scan runs 2^20 clean, so the trigger is the added broadcast
planes' composition) — the same scan-length-sensitive XLA bug family
the SCAMP plane hit.  Round 5 found 2^20 dense SCAMP runs CLEAN when
the scan is chunked into <=50-round launches; this script asks the
same question of the pt plane: chain L launches of a bounded-length
scan (flat cadence) or bounded-block staggered scan and see whether
the chunked shape survives where the long scan faulted.

Run:  python scripts/repro_pt_dense_fault.py [rounds_per_launch
          [log2_n]] [--launches L] [--flat]  (default: staggered
          cadence, rounds_per_launch rounded to whole 2k-blocks)
"""
import argparse
import os
import sys

os.environ["PARTISAN_TPU_UNGATE"] = "1"

import jax
import jax.numpy as jnp

sys.path.insert(0, '.')
from partisan_tpu.config import Config
from partisan_tpu.models.hyparview_dense import dense_init
from partisan_tpu.models.plumtree_dense import (pt_dense_init,
                                                run_pt_dense,
                                                run_pt_dense_staggered)

ap = argparse.ArgumentParser()
ap.add_argument("rounds", nargs="?", type=int, default=50)
ap.add_argument("log2_n", nargs="?", type=int, default=20)
ap.add_argument("--launches", type=int, default=4)
ap.add_argument("--flat", action="store_true",
                help="every-round cadence (run_pt_dense) instead of "
                     "the staggered block cadence")
args = ap.parse_args()

k = 5
cfg = Config(n_nodes=1 << args.log2_n, seed=7)
blocks = max(1, args.rounds // (2 * k))
per = args.rounds if args.flat else blocks * 2 * k
print(f"device={jax.devices()[0]} n={cfg.n_nodes} per_launch={per} "
      f"launches={args.launches} cadence="
      f"{'flat' if args.flat else f'ref10/5k{k}'}", flush=True)
hv = dense_init(cfg)
ptd = pt_dense_init(cfg)
hv.active.block_until_ready()
for i in range(args.launches):
    if args.flat:
        hv, ptd = run_pt_dense(hv, ptd, args.rounds, cfg, 0.01)
    else:
        hv, ptd = run_pt_dense_staggered(hv, ptd, blocks, cfg, 0.01,
                                         0, k)
    print(f"launch {i}: root_seq={int(ptd.seq[0])} "
          f"tracked={float(jnp.mean((ptd.seq[0] - ptd.seq) <= 5)):.3f}",
          flush=True)
print("clean exit", flush=True)
