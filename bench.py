"""Headline benchmark — BASELINE config #5.

`protocols/demers_rumor_mongering.erl` at 10^6 simulated nodes with 1%/round
churn.  Target (BASELINE.json): >= 10^6 nodes at >= 1000 gossip rounds/sec on
TPU v5e-8; this harness runs on whatever jax.devices() offers (the driver
gives one v5e chip) and reports rounds/sec, with vs_baseline = value / 1000.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

from partisan_tpu.models.demers import rumor_init, rumor_run


def main() -> None:
    n = 1_000_000
    churn = 0.01
    fanout = 2
    rounds = 1000

    w = rumor_init(n)
    # warmup / compile
    w1 = rumor_run(w, 10, n, fanout, 1, churn)
    jax.block_until_ready(w1)

    t0 = time.perf_counter()
    out = rumor_run(w, rounds, n, fanout, 1, churn)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    rps = rounds / dt
    infected = float(jnp.mean(out.infected))
    result = {
        "metric": f"rumor_mongering rounds/sec @ N=1e6, churn={churn}",
        "value": round(rps, 1),
        "unit": "rounds/sec",
        "vs_baseline": round(rps / 1000.0, 3),
    }
    print(json.dumps(result))
    print(f"# infected fraction after {rounds} rounds: {infected:.3f}; "
          f"device={jax.devices()[0].platform}", file=sys.stderr)


if __name__ == "__main__":
    main()
