"""Headline benchmark — BASELINE config #5.

`protocols/demers_rumor_mongering.erl` at >= 10^6 simulated nodes with
1%/round churn.  Target (BASELINE.json): >= 10^6 nodes at >= 1000 gossip
rounds/sec on TPU v5e-8; this harness runs on whatever jax.devices() offers
(the driver gives one v5e chip) and reports sustained rounds/sec, with
vs_baseline = value / 1000.

The kernel is the fused pallas mega-kernel (ops/rumor_kernel.py): the whole
multi-round run is ONE kernel launch with the node state packed as uint32
bitsets resident in VMEM, per-round randomness from the on-core PRNG, and
shift-rendezvous delivery as dynamic circular rotations.  N = 2^20
(1,048,576 >= 10^6 — the kernel wants a multiple of 4096).  Falls back to
the XLA "packed" lax.scan path if pallas is unavailable on the device.

Measurement notes (learned the hard way):
  * each timed trial uses a DIFFERENT initial world — the TPU tunnel
    caches identical (executable, input) executions;
  * `jax.block_until_ready` can return before remote execution finishes
    under the tunnel, so every trial syncs on a scalar readback;
  * one 20k-round run per trial amortizes the ~100 ms per-call dispatch
    latency that otherwise dominates (and used to understate the rate 10x).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import os
import statistics
import sys
import time

import jax
import jax.numpy as jnp

from partisan_tpu.models.demers import rumor_init, rumor_run
from partisan_tpu.telemetry.observatory import CompileLedger
from partisan_tpu.telemetry.sinks import JsonlSink


def main() -> None:
    n = 1 << 20          # 1,048,576 simulated nodes
    churn = 0.01
    fanout = 2
    rounds = 20_000
    trials = 5

    # compile observatory (ISSUE 14): the headline bench's compile cost
    # lands in the shared ledger, attributed per variant — after a
    # kernel edit, scripts/observatory.py --report shows what the first
    # trial run paid before a single timed window opened.  File-only;
    # the stdout contract below is untouched.
    ledger = CompileLedger(path=os.environ.get(
        "PARTISAN_COMPILE_LEDGER", "COMPILE_ledger.jsonl")).install()

    # On TPU the pallas kernel MUST run — a regression there should fail
    # the bench loudly, not silently report the ~10x-slower packed number.
    # Only a non-TPU device (the CPU fallback environment) may fall back.
    variant = "pallas"
    try:
        with ledger.attribute("bench_rumor_pallas_n2e20"):
            out = rumor_run(rumor_init(n, 0), rounds, n, fanout, 1, churn,
                            variant)
            float(jnp.sum(out.infected))      # compile + real sync
    except Exception as e:                    # noqa: BLE001
        if jax.devices()[0].platform == "tpu":
            raise
        print(f"# pallas path unavailable off-TPU ({type(e).__name__}: "
              f"{e}); falling back to XLA packed scan", file=sys.stderr)
        variant = "packed"
        with ledger.attribute("bench_rumor_packed_n2e20"):
            out = rumor_run(rumor_init(n, 0), rounds, n, fanout, 1, churn,
                            variant)
            float(jnp.sum(out.infected))
    ledger.close()                            # compiles done; stop listening

    # one untimed priming run on a fresh input: the first post-compile
    # execution is consistently a low outlier (device/tunnel spin-up)
    out = rumor_run(rumor_init(n, 991), rounds, n, fanout, 1, churn, variant)
    float(jnp.sum(out.infected))

    rates = []
    infected = 0.0
    # per-invocation salt: the tunnel's (executable, input) result
    # cache persists ACROSS processes, so seeds merely distinct within
    # one run can still replay a previous invocation's execution as a
    # near-instant bogus trial (observed on the perf-suite's 1e6 row:
    # a fixed timed seed read back 600k rounds/s)
    salt = int.from_bytes(os.urandom(4), "little")
    # per-trial rows go through the telemetry JSONL sink so BENCH_*
    # snapshots gain a per-trial artifact; stdout stays the one parsed
    # JSON summary line (contract unchanged).  Rows are BUFFERED and
    # written after the whole trial loop (round 6): the r5 flagship
    # number read low vs r3/r4 and the bisect had to rule the sink's
    # between-trial host I/O in or out — now it is structurally out of
    # every inter-trial window, not just outside the timed regions.
    trial_rows = []
    for t in range(trials):
        w = rumor_init(n, (7919 * (t + 101) + salt) % n)
        t0 = time.perf_counter()
        out = rumor_run(w, rounds, n, fanout, 1, churn, variant)
        infected = float(jnp.mean(out.infected))   # scalar readback = sync
        dt = time.perf_counter() - t0
        rates.append(rounds / dt)
        trial_rows.append({
            "trial": t, "seconds": dt, "rounds_per_sec": rounds / dt,
            "rounds": rounds, "n": n, "churn": churn, "fanout": fanout,
            "variant": variant, "infected": infected,
            "device": jax.devices()[0].platform, "t_wall": time.time(),
        })
    trial_sink = JsonlSink(
        os.environ.get("PARTISAN_BENCH_JSONL", "BENCH_trials.jsonl"))
    for row in trial_rows:
        trial_sink.write_row(row)
    trial_sink.close()

    # unified bench ledger (ISSUE 18): the same trials as canonical
    # BenchRows — calibration-normalized, with the compile wall the
    # CompileLedger attributed to this variant.  BENCH_trials.jsonl and
    # the stdout contract above stay byte-identical.
    from partisan_tpu.telemetry import benchplane
    compile_s = ledger.summary().get(
        f"bench_rumor_{variant}_n2e20", {}).get("compile_s")
    calib = benchplane.calibrate()
    benchplane.append_rows_nonfatal([benchplane.make_row(
        "bench_rumor", variant,
        config={"churn": churn, "fanout": fanout},
        n_nodes=n, rounds=rounds,
        rounds_per_sec=row["rounds_per_sec"], wall_s=row["seconds"],
        compile_s=(compile_s if row["trial"] == 0 else None),
        calibration=calib,
        metrics={"trial": row["trial"], "infected": row["infected"]})
        for row in trial_rows])

    rps = statistics.median(rates)
    result = {
        "metric": f"rumor_mongering rounds/sec @ N=2^20, churn={churn}",
        "value": round(rps, 1),
        "unit": "rounds/sec",
        "vs_baseline": round(rps / 1000.0, 3),
        "variant": variant,
    }
    print(json.dumps(result))
    print(f"# variant={variant}, trials={['%.0f' % r for r in rates]}, "
          f"infected fraction after {rounds} rounds: {infected:.3f}; "
          f"device={jax.devices()[0].platform}", file=sys.stderr)


if __name__ == "__main__":
    main()
