"""Headline benchmark — BASELINE config #5.

`protocols/demers_rumor_mongering.erl` at 10^6 simulated nodes with 1%/round
churn.  Target (BASELINE.json): >= 10^6 nodes at >= 1000 gossip rounds/sec on
TPU v5e-8; this harness runs on whatever jax.devices() offers (the driver
gives one v5e chip) and reports rounds/sec, with vs_baseline = value / 1000.

The kernel is the shift-rendezvous fast path (models/demers.py: push
delivery as jnp.roll — streaming HBM-bound rounds instead of serialized
2M-index scatters).  Each timed trial uses a DIFFERENT initial world: the
TPU tunnel caches identical (executable, input) executions, so re-timing
the warmup input reports dispatch latency, not execution.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import statistics
import sys
import time

import jax
import jax.numpy as jnp

from partisan_tpu.models.demers import rumor_init, rumor_run


def main() -> None:
    n = 1_000_000
    churn = 0.01
    fanout = 2
    rounds = 1000
    trials = 5

    # compile with the SAME static round count (a different count would
    # leave the timed call paying a fresh scan compile)
    out = rumor_run(rumor_init(n, 0), rounds, n, fanout, 1, churn)
    jax.block_until_ready(out)

    rates = []
    infected = 0.0
    for t in range(trials):
        # distinct, unlikely-reused patient-zero rows so no trial can hit
        # a stale tunnel cache entry from an earlier process
        w = rumor_init(n, patient_zero=(7919 * (t + 1)) % n)
        t0 = time.perf_counter()
        out = rumor_run(w, rounds, n, fanout, 1, churn)
        jax.block_until_ready(out)
        rates.append(rounds / (time.perf_counter() - t0))
        infected = float(jnp.mean(out.infected))

    rps = statistics.median(rates)
    result = {
        "metric": f"rumor_mongering rounds/sec @ N=1e6, churn={churn}",
        "value": round(rps, 1),
        "unit": "rounds/sec",
        "vs_baseline": round(rps / 1000.0, 3),
    }
    print(json.dumps(result))
    print(f"# trials={['%.0f' % r for r in rates]}, infected fraction after "
          f"{rounds} rounds: {infected:.3f}; "
          f"device={jax.devices()[0].platform}", file=sys.stderr)


if __name__ == "__main__":
    main()
